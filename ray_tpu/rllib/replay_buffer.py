"""Replay buffers: uniform ring + proportional prioritized (sum-tree).

Reference analogs: rllib/utils/replay_buffers/replay_buffer.py
(ReplayBuffer.add/sample) and prioritized_replay_buffer.py (the
proportional variant of Schaul et al. PER, sum-tree backed).  Fresh
numpy implementation; storage is columnar (one preallocated array per
SampleBatch column) so sampling a minibatch is one fancy-index per
column — the host-side cost that feeds the TPU learner.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform-sampling ring buffer of transitions."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return self._size

    def _ensure_storage(self, batch: SampleBatch) -> None:
        for k, v in batch.items():
            if k not in self._cols:
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         dtype=v.dtype)

    def add(self, batch: SampleBatch) -> np.ndarray:
        """Append a batch of rows; returns the storage indices used."""
        self._ensure_storage(batch)
        n = batch.count
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        return idx

    def sample(self, num_rows: int) -> SampleBatch:
        idx = self._rng.randint(0, self._size, size=num_rows)
        return self._take(idx)

    def _take(self, idx: np.ndarray) -> SampleBatch:
        return SampleBatch({k: c[idx] for k, c in self._cols.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (sum-tree, O(log n) updates).

    sample() returns (batch, indices, is_weights); callers feed TD
    errors back through update_priorities(indices, errors).
    """

    def __init__(self, capacity: int, *, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed=seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        # binary-heap-layout sum tree over `capacity` leaves
        self._tree_size = 1
        while self._tree_size < self.capacity:
            self._tree_size *= 2
        self._tree = np.zeros(2 * self._tree_size, dtype=np.float64)
        self._max_priority = 1.0

    # -- sum tree ---------------------------------------------------------
    def _set_priorities(self, idx: np.ndarray, prio: np.ndarray) -> None:
        pos = idx + self._tree_size
        self._tree[pos] = prio
        pos //= 2
        while np.any(pos >= 1):
            pos = np.unique(pos[pos >= 1])
            self._tree[pos] = self._tree[2 * pos] + self._tree[2 * pos + 1]
            pos //= 2

    def _prefix_find(self, mass: np.ndarray) -> np.ndarray:
        """Vectorized descent: for each probability mass, the leaf whose
        prefix-sum interval contains it."""
        pos = np.ones(len(mass), dtype=np.int64)
        mass = mass.copy()
        while pos[0] < self._tree_size:
            left = 2 * pos
            left_mass = self._tree[left]
            go_right = mass > left_mass
            mass = np.where(go_right, mass - left_mass, mass)
            pos = np.where(go_right, left + 1, left)
        return pos - self._tree_size

    # -- buffer API -------------------------------------------------------
    def add(self, batch: SampleBatch) -> np.ndarray:
        idx = super().add(batch)
        self._set_priorities(
            idx, np.full(len(idx), self._max_priority ** self.alpha))
        return idx

    def sample(self, num_rows: int
               ) -> Tuple[SampleBatch, np.ndarray, np.ndarray]:
        total = self._tree[1]
        mass = self._rng.uniform(0.0, total, size=num_rows)
        idx = np.clip(self._prefix_find(mass), 0, self._size - 1)
        prios = self._tree[idx + self._tree_size]
        probs = np.maximum(prios, 1e-12) / max(total, 1e-12)
        weights = (self._size * probs) ** (-self.beta)
        weights /= weights.max()
        return self._take(idx), idx, weights.astype(np.float32)

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prio = (np.abs(td_errors) + self.eps) ** self.alpha
        self._max_priority = max(self._max_priority,
                                 float(np.abs(td_errors).max(initial=0.0)
                                       + self.eps))
        self._set_priorities(np.asarray(idx), prio)
