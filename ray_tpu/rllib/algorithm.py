"""Algorithm: the trainable RL loop (reference analog:
rllib/algorithms/algorithm.py:150 Algorithm(Trainable), :728 step).

`train()` runs one training iteration and returns a metrics dict; the
class also works as a tune trainable via `as_trainable()` (iterating
train() and reporting each result), matching how the reference runs
learning tests through tune.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class AlgorithmConfig:
    env: Any = None
    env_config: Optional[Dict[str, Any]] = None
    num_workers: int = 2
    num_envs_per_worker: int = 1
    rollout_fragment_length: int = 200
    train_batch_size: int = 4000
    gamma: float = 0.99
    lr: float = 3e-4
    seed: int = 0
    num_cpus_per_worker: float = 1.0
    # learner placement: {"TPU": 1} puts the learner policy on the chip
    learner_resources: Optional[Dict[str, float]] = None
    #: run greedy-policy evaluation every N train() iterations on a
    #: dedicated worker (reference: evaluation_interval +
    #: evaluation WorkerSet, algorithm.py evaluate()); 0 = off
    evaluation_interval: int = 0
    evaluation_num_episodes: int = 10

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def update(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown config field {k!r}")
            setattr(self, k, v)
        return self


class Algorithm:
    _config_cls = AlgorithmConfig

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self._episode_returns: List[float] = []
        self.setup(config)

    # -- subclass surface -------------------------------------------------
    def setup(self, config: AlgorithmConfig) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def cleanup(self) -> None:
        pass

    # -- public API -------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        start = time.monotonic()
        result = self.training_step()
        self.iteration += 1
        self._timesteps_total += result.get("timesteps_this_iter", 0)
        recent = self._episode_returns[-100:]
        result.update({
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "episode_reward_mean": (sum(recent) / len(recent))
            if recent else float("nan"),
            "episodes_total": len(self._episode_returns),
            "time_this_iter_s": time.monotonic() - start,
        })
        interval = getattr(self.config, "evaluation_interval", 0)
        if interval and self.iteration % interval == 0:
            result["evaluation"] = self.evaluate()
        return result

    def evaluate(self) -> Dict[str, Any]:
        """Greedy-policy evaluation on a dedicated worker (reference:
        Algorithm.evaluate over the evaluation WorkerSet).  Subclasses
        that support it implement ``_make_eval_worker``; the worker is
        created lazily and reused, with weights synced per call."""
        import ray_tpu

        factory = getattr(self, "_make_eval_worker", None)
        if factory is None:
            raise NotImplementedError(
                f"{type(self).__name__} does not support evaluation")
        if getattr(self, "_eval_worker", None) is None:
            self._eval_worker = factory()
        w = self._eval_worker
        ray_tpu.get(w.set_weights.remote(
            self._eval_weights()), timeout=60)
        fs = getattr(self, "_filter_state", None)
        if fs is not None:
            # evaluation must normalize with the TRAINING statistics
            ray_tpu.get(w.set_filter_state.remote(fs), timeout=60)
        return ray_tpu.get(w.evaluate.remote(
            getattr(self.config, "evaluation_num_episodes", 10)),
            timeout=600)

    def stop(self) -> None:
        self.cleanup()

    # -- checkpointing (reference: Algorithm.save / Algorithm.restore) ----
    _WEIGHT_ATTRS = ("learner_policy", "policy", "net", "main",
                     "exploiter")
    _RAW_ATTRS = ("params", "model_params", "theta")
    #: plain scalar counters driving schedules (epsilon decay, target
    #: sync cadence) — without them a resumed run re-explores from
    #: scratch and re-gates behind learning_starts
    _COUNTER_ATTRS = ("_env_steps", "_last_target_sync")

    def _checkpoint_state(self) -> Dict[str, Any]:
        """Learner state as numpy pytrees — every weight-bearing attr
        this algorithm exposes (policies with get_weights, raw param
        trees, ES/ARS theta vectors)."""
        import jax

        state: Dict[str, Any] = {}
        for attr in self._WEIGHT_ATTRS:
            obj = getattr(self, attr, None)
            if obj is not None and hasattr(obj, "get_weights"):
                state[attr] = obj.get_weights()
                for tname in ("target", "target_params"):
                    tgt = getattr(obj, tname, None)
                    if tgt is not None:
                        # target nets are saved EXACTLY (structure
                        # varies per policy — SAC's is a critic
                        # subset); a restored off-policy run must not
                        # bootstrap TD from a random target until the
                        # next sync
                        state[f"{attr}::{tname}"] = jax.tree.map(
                            np.asarray, tgt)
        for attr in self._RAW_ATTRS:
            val = getattr(self, attr, None)
            if val is not None:
                state[attr] = jax.tree.map(np.asarray, val)
        if not state:
            raise NotImplementedError(
                f"{type(self).__name__} exposes no checkpointable "
                "state")
        fs = getattr(self, "_filter_state", None)
        if fs is not None:
            # observation-filter statistics are part of the policy:
            # restored weights without them see unnormalized inputs
            state["_filter_state"] = fs
        for attr in self._COUNTER_ATTRS:
            val = getattr(self, attr, None)
            if val is not None:
                state[attr] = val
        return state

    def _restore_state(self, state: Dict[str, Any]) -> None:
        for attr, val in state.items():
            if "::" in attr:
                continue            # applied with its owner below
            obj = getattr(self, attr, None)
            if obj is not None and hasattr(obj, "set_weights"):
                obj.set_weights(val)
                for tname in ("target", "target_params"):
                    tgt = state.get(f"{attr}::{tname}")
                    if tgt is not None:
                        setattr(obj, tname, tgt)
            elif attr in self._WEIGHT_ATTRS:
                # a policy slot the checkpoint fills but this config
                # did not construct (e.g. train_exploiter=False
                # restoring an exploiter-bearing checkpoint): writing
                # the raw dict would explode later — fail loudly now
                raise ValueError(
                    f"checkpoint carries {attr!r} weights but this "
                    f"{type(self).__name__} config did not construct "
                    f"that policy")
            else:
                setattr(self, attr, val)

    def save(self, checkpoint_dir: str) -> str:
        """Write a restorable checkpoint; returns its path."""
        import os
        import pickle

        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm.pkl")
        # write-then-rename: a crash mid-dump must never truncate the
        # previous good checkpoint at the same path
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"state": self._checkpoint_state(),
                         "iteration": self.iteration,
                         "timesteps_total": self._timesteps_total,
                         "algorithm": type(self).__name__}, f)
        os.replace(tmp, path)
        return path

    def restore(self, path: str) -> None:
        """Load weights + counters saved by ``save`` into this
        (already-constructed) algorithm."""
        import os
        import pickle

        if os.path.isdir(path):
            path = os.path.join(path, "algorithm.pkl")
        with open(path, "rb") as f:
            blob = pickle.load(f)
        saved = blob.get("algorithm")
        if saved and saved != type(self).__name__:
            raise ValueError(
                f"checkpoint was saved by {saved}, cannot restore "
                f"into {type(self).__name__}")
        self._restore_state(blob["state"])
        self.iteration = blob.get("iteration", 0)
        self._timesteps_total = blob.get("timesteps_total", 0)
        # rollout workers must act with the restored weights (and the
        # restored observation-filter statistics)
        weights = None
        for attr in ("learner_policy", "policy", "net"):
            obj = getattr(self, attr, None)
            if obj is not None and hasattr(obj, "get_weights"):
                weights = obj.get_weights()
                break
        sync = getattr(self, "workers", None)
        if weights is not None and sync is not None:
            import ray_tpu

            if hasattr(sync, "sync_weights"):      # WorkerSet
                sync.sync_weights(weights)
                # RolloutWorkers (the WorkerSet members) implement
                # set_filter_state; raw-list worker classes
                # (TransitionWorker etc.) do not, and actor handles
                # fabricate methods on attribute access, so the push
                # is gated on the WorkerSet case rather than hasattr
                fs = getattr(self, "_filter_state", None)
                if fs is not None:
                    ray_tpu.get(
                        [w.set_filter_state.remote(fs)
                         for w in getattr(sync, "workers", [])],
                        timeout=60.0)
            elif isinstance(sync, (list, tuple)) and sync:
                ref = ray_tpu.put(weights)
                ray_tpu.get([w.set_weights.remote(ref)
                             for w in sync], timeout=60.0)

    @classmethod
    def as_trainable(cls, base_config: AlgorithmConfig,
                     stop_iters: int = 10) -> Callable:
        """Function trainable for ray_tpu.tune (reference: Algorithm IS a
        Trainable; here the adapter closes over the config)."""

        def trainable(config: Dict[str, Any]):
            from ray_tpu.air import session

            overrides = dict(config or {})
            # per-trial loop bound (tune.run("PPO", config={...,
            # "training_iterations": N}) routes through here)
            iters = int(overrides.pop("training_iterations",
                                      stop_iters))
            cfg = base_config.copy().update(**overrides)
            algo = cls(cfg)
            try:
                for _ in range(iters):
                    session.report(algo.train())
            finally:
                algo.stop()

        trainable.__name__ = cls.__name__
        return trainable


def learner_mesh(learner_devices: int):
    """Local data mesh for multi-device learner updates (shared by
    PPO/IMPALA/DQN setup); None when learner_devices <= 1."""
    if learner_devices <= 1:
        return None
    import jax

    from ray_tpu.parallel import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=learner_devices),
                     devices=jax.devices()[:learner_devices])
