"""Algorithm: the trainable RL loop (reference analog:
rllib/algorithms/algorithm.py:150 Algorithm(Trainable), :728 step).

`train()` runs one training iteration and returns a metrics dict; the
class also works as a tune trainable via `as_trainable()` (iterating
train() and reporting each result), matching how the reference runs
learning tests through tune.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class AlgorithmConfig:
    env: Any = None
    env_config: Optional[Dict[str, Any]] = None
    num_workers: int = 2
    num_envs_per_worker: int = 1
    rollout_fragment_length: int = 200
    train_batch_size: int = 4000
    gamma: float = 0.99
    lr: float = 3e-4
    seed: int = 0
    num_cpus_per_worker: float = 1.0
    # learner placement: {"TPU": 1} puts the learner policy on the chip
    learner_resources: Optional[Dict[str, float]] = None

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def update(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown config field {k!r}")
            setattr(self, k, v)
        return self


class Algorithm:
    _config_cls = AlgorithmConfig

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self._episode_returns: List[float] = []
        self.setup(config)

    # -- subclass surface -------------------------------------------------
    def setup(self, config: AlgorithmConfig) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def cleanup(self) -> None:
        pass

    # -- public API -------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        start = time.monotonic()
        result = self.training_step()
        self.iteration += 1
        self._timesteps_total += result.get("timesteps_this_iter", 0)
        recent = self._episode_returns[-100:]
        result.update({
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "episode_reward_mean": (sum(recent) / len(recent))
            if recent else float("nan"),
            "episodes_total": len(self._episode_returns),
            "time_this_iter_s": time.monotonic() - start,
        })
        return result

    def stop(self) -> None:
        self.cleanup()

    @classmethod
    def as_trainable(cls, base_config: AlgorithmConfig,
                     stop_iters: int = 10) -> Callable:
        """Function trainable for ray_tpu.tune (reference: Algorithm IS a
        Trainable; here the adapter closes over the config)."""

        def trainable(config: Dict[str, Any]):
            from ray_tpu.air import session

            cfg = base_config.copy().update(**config)
            algo = cls(cfg)
            try:
                for _ in range(stop_iters):
                    session.report(algo.train())
            finally:
                algo.stop()

        trainable.__name__ = cls.__name__
        return trainable


def learner_mesh(learner_devices: int):
    """Local data mesh for multi-device learner updates (shared by
    PPO/IMPALA/DQN setup); None when learner_devices <= 1."""
    if learner_devices <= 1:
        return None
    import jax

    from ray_tpu.parallel import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=learner_devices),
                     devices=jax.devices()[:learner_devices])
