"""Vectorized environments: N env copies stepped as ONE batched call.

Reference analog: rllib/env/vector_env.py:24 (VectorEnv /
_VectorizedGymEnv).  Redesigned numpy-first instead of list-of-envs
first: the interface speaks (N, ...) arrays end to end, auto-resets
finished sub-envs internally (the pre-reset terminal observation is
surfaced in ``infos["final_obs"]`` for truncation bootstrapping), and
natively-batched envs implement dynamics directly over the batch axis —
one numpy expression steps all N copies, which is where the rollout
samples/s comes from (a python for-loop over gym envs caps a CartPole
worker at ~10k steps/s; the batched physics below does >100k).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    """Batched env interface.

    ``vector_step`` consumes an (N,) or (N, action_dim) action array and
    returns ``(obs, rewards, terminateds, truncateds, infos)`` where the
    first four are (N, ...) arrays.  Sub-envs that finish are reset
    INSIDE the call; ``obs`` rows for finished envs are the fresh
    post-reset observations, and ``infos["final_obs"]`` holds the
    pre-reset terminal observation for every finished row (needed to
    bootstrap truncated episodes with V(s_T))."""

    num_envs: int
    observation_space: Any = None  # single-env spaces
    action_space: Any = None

    def vector_reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def vector_step(self, actions) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray, np.ndarray, Dict]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SyncVectorEnv(VectorEnv):
    """Fallback vectorization: a python loop over per-copy gym envs, for
    envs without a batched implementation.  Same interface/semantics as
    the native path so workers never branch."""

    def __init__(self, make_env: Callable[[], Any], num_envs: int,
                 first_env: Any = None):
        self.envs = ([first_env] if first_env is not None else []) + \
            [make_env() for _ in range(num_envs
                                       - (first_env is not None))]
        self.num_envs = num_envs
        self.observation_space = getattr(self.envs[0],
                                         "observation_space", None)
        self.action_space = getattr(self.envs[0], "action_space", None)

    def vector_reset(self, seed=None):
        obs = [e.reset(seed=None if seed is None else seed + i)[0]
               for i, e in enumerate(self.envs)]
        return np.asarray(obs, np.float32)

    def vector_step(self, actions):
        n = self.num_envs
        obs_out, rews = [None] * n, np.zeros(n, np.float32)
        terms = np.zeros(n, np.bool_)
        truncs = np.zeros(n, np.bool_)
        final_obs = [None] * n
        for i, env in enumerate(self.envs):
            o2, r, term, trunc, _ = env.step(actions[i])
            rews[i], terms[i], truncs[i] = r, term, trunc
            if term or trunc:
                final_obs[i] = np.asarray(o2, np.float32)
                o2 = env.reset()[0]
            obs_out[i] = o2
        obs_arr = np.asarray(obs_out, np.float32)
        fo = np.array([obs_arr[i] if f is None else f
                       for i, f in enumerate(final_obs)], np.float32)
        return obs_arr, rews, terms, truncs, {"final_obs": fo}

    def close(self):
        for e in self.envs:
            if hasattr(e, "close"):
                e.close()


class CartPoleVecEnv(VectorEnv):
    """Natively-batched CartPole-v1: the classic cart-pole swing-up
    physics (Barto/Sutton/Anderson 1983 equations) over an (N, 4) state
    matrix — every step is a handful of vectorized numpy expressions.

    Matches the gymnasium CartPole-v1 task spec: force ±10 N, Euler
    integration at tau=0.02 s, termination at |x|>2.4 or |theta|>12°,
    truncation at 500 steps, reward 1 per step, uniform(-0.05, 0.05)
    initial state."""

    _GRAVITY = 9.8
    _M_CART = 1.0
    _M_POLE = 0.1
    _LEN = 0.5            # half pole length
    _FORCE = 10.0
    _TAU = 0.02
    _X_LIMIT = 2.4
    _THETA_LIMIT = 12 * np.pi / 180
    _MAX_STEPS = 500

    def __init__(self, num_envs: int, seed: int = 0):
        import gymnasium as gym

        self.num_envs = num_envs
        self.observation_space = gym.spaces.Box(
            -np.inf, np.inf, (4,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._rng = np.random.RandomState(seed)
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros(num_envs, np.int64)

    def _reset_rows(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(
                -0.05, 0.05, size=(n, 4))
            self._steps[mask] = 0

    def vector_reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._reset_rows(np.ones(self.num_envs, np.bool_))
        return self._state.astype(np.float32)

    def vector_step(self, actions):
        x, x_dot, th, th_dot = self._state.T
        force = np.where(np.asarray(actions) == 1, self._FORCE,
                         -self._FORCE)
        cos, sin = np.cos(th), np.sin(th)
        total_m = self._M_CART + self._M_POLE
        pole_ml = self._M_POLE * self._LEN
        temp = (force + pole_ml * th_dot ** 2 * sin) / total_m
        th_acc = (self._GRAVITY * sin - cos * temp) / (
            self._LEN * (4.0 / 3.0 - self._M_POLE * cos ** 2 / total_m))
        x_acc = temp - pole_ml * th_acc * cos / total_m
        # Euler, update-then-integrate order of the classic task
        x = x + self._TAU * x_dot
        x_dot = x_dot + self._TAU * x_acc
        th = th + self._TAU * th_dot
        th_dot = th_dot + self._TAU * th_acc
        self._state = np.stack([x, x_dot, th, th_dot], axis=1)
        self._steps += 1

        terms = (np.abs(x) > self._X_LIMIT) | (np.abs(th)
                                               > self._THETA_LIMIT)
        truncs = ~terms & (self._steps >= self._MAX_STEPS)
        rews = np.ones(self.num_envs, np.float32)
        final_obs = self._state.astype(np.float32)
        done = terms | truncs
        self._reset_rows(done)
        return (self._state.astype(np.float32), rews,
                terms, truncs, {"final_obs": final_obs})


def make_vector_env(env: Any, env_config: Optional[Dict], num_envs: int,
                    seed: int = 0) -> VectorEnv:
    """Build the fastest available VectorEnv for ``env``:

    - an env creator may return a VectorEnv directly (fully native; its
      own num_envs wins over the requested one);
    - known classic-control names get the batched-numpy implementation;
    - anything else is wrapped per-copy in SyncVectorEnv."""
    if callable(env):
        probe = env(env_config or {})
        if isinstance(probe, VectorEnv):
            return probe
        # reuse the probe as the first sub-env — env construction can be
        # expensive (simulators), don't throw one away per worker
        return SyncVectorEnv(lambda: env(env_config or {}), num_envs,
                             first_env=probe)
    if env == "CartPole-v1":
        return CartPoleVecEnv(num_envs, seed=seed)
    if env == "MinAtarBreakout":
        from ray_tpu.rllib.envs import MinAtarBreakoutVecEnv

        return MinAtarBreakoutVecEnv(
            num_envs, size=int((env_config or {}).get("size", 10)),
            seed=seed)
    if env == "RepeatPrev":
        from ray_tpu.rllib.envs import RepeatPrevVecEnv

        return RepeatPrevVecEnv(
            num_envs,
            n_symbols=int((env_config or {}).get("n_symbols", 3)),
            seed=seed)
    import gymnasium as gym

    return SyncVectorEnv(lambda: gym.make(env), num_envs)
