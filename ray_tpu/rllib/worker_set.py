"""WorkerSet: the gang of remote RolloutWorker actors plus a local
learner-side policy (reference analog: rllib/evaluation/worker_set.py:64)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.rllib.policy import JaxPolicy, PolicySpec
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sample_batch import SampleBatch


class WorkerSet:
    def __init__(self, *, num_workers: int, env: Any,
                 env_config: Optional[Dict] = None,
                 policy_spec: PolicySpec,
                 num_envs_per_worker: int = 1,
                 rollout_fragment_length: int = 200,
                 gamma: float = 0.99, lam: float = 0.95,
                 num_cpus_per_worker: float = 1.0, seed: int = 0):
        self.num_workers = num_workers
        kwargs = dict(env=env, env_config=env_config,
                      policy_spec=policy_spec,
                      num_envs=num_envs_per_worker, gamma=gamma, lam=lam,
                      rollout_fragment_length=rollout_fragment_length)
        remote_cls = ray_tpu.remote(num_cpus=num_cpus_per_worker)(
            RolloutWorker)
        self.workers = [remote_cls.remote(seed=seed + 1000 * (i + 1),
                                          **kwargs)
                        for i in range(num_workers)]

    def sample(self, timeout: float = 300.0) -> List[SampleBatch]:
        """reference rollout_ops.py:36 synchronous_parallel_sample."""
        return ray_tpu.get([w.sample.remote() for w in self.workers],
                           timeout=timeout)

    def sync_weights(self, weights, timeout: float = 60.0) -> None:
        """Broadcast learner weights via one object-store put."""
        ref = ray_tpu.put(weights)
        ray_tpu.get([w.set_weights.remote(ref) for w in self.workers],
                    timeout=timeout)

    def episode_returns(self, timeout: float = 60.0) -> List[float]:
        parts = ray_tpu.get(
            [w.pop_episode_returns.remote() for w in self.workers],
            timeout=timeout)
        return [r for p in parts for r in p]

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []
