"""WorkerSet: the gang of remote RolloutWorker actors plus a local
learner-side policy (reference analog: rllib/evaluation/worker_set.py:64)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.rllib.policy import JaxPolicy, PolicySpec
from ray_tpu.rllib.rollout_worker import RolloutWorker
from ray_tpu.rllib.sample_batch import SampleBatch


class WorkerSet:
    def __init__(self, *, num_workers: int, env: Any,
                 env_config: Optional[Dict] = None,
                 policy_spec: PolicySpec,
                 num_envs_per_worker: int = 1,
                 rollout_fragment_length: int = 200,
                 gamma: float = 0.99, lam: float = 0.95,
                 num_cpus_per_worker: float = 1.0, seed: int = 0,
                 observation_filter: str = "NoFilter",
                 worker_cls: Optional[type] = None,
                 async_sampling: bool = False):
        self.num_workers = num_workers
        kwargs = dict(env=env, env_config=env_config,
                      policy_spec=policy_spec,
                      num_envs=num_envs_per_worker, gamma=gamma, lam=lam,
                      rollout_fragment_length=rollout_fragment_length,
                      observation_filter=observation_filter,
                      async_sampling=async_sampling)
        remote_cls = ray_tpu.remote(num_cpus=num_cpus_per_worker)(
            worker_cls or RolloutWorker)
        self.workers = [remote_cls.remote(seed=seed + 1000 * (i + 1),
                                          **kwargs)
                        for i in range(num_workers)]

    def sample(self, timeout: float = 300.0) -> List[SampleBatch]:
        """reference rollout_ops.py:36 synchronous_parallel_sample."""
        return ray_tpu.get([w.sample.remote() for w in self.workers],
                           timeout=timeout)

    def sync_weights(self, weights, timeout: float = 60.0) -> None:
        """Broadcast learner weights via one object-store put."""
        ref = ray_tpu.put(weights)
        ray_tpu.get([w.set_weights.remote(ref) for w in self.workers],
                    timeout=timeout)

    def episode_returns(self, timeout: float = 60.0) -> List[float]:
        parts = ray_tpu.get(
            [w.pop_episode_returns.remote() for w in self.workers],
            timeout=timeout)
        return [r for p in parts for r in p]

    def sync_filters(self, global_state, timeout: float = 60.0):
        """Pull each worker's since-last-sync DELTA, merge into the
        coordinator's global state, broadcast the merged state back;
        returns the new global state (reference:
        FilterManager.synchronize — deltas, never full states, so shared
        history is counted exactly once)."""
        from ray_tpu.rllib.filters import merge_filter_states

        deltas = ray_tpu.get(
            [w.pop_filter_delta.remote() for w in self.workers],
            timeout=timeout)
        merged = merge_filter_states(
            ([global_state] if global_state else []) + deltas)
        if merged.get("type") == "NoFilter":
            return global_state
        ray_tpu.get(
            [w.set_filter_state.remote(merged) for w in self.workers],
            timeout=timeout)
        return merged

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []
