"""Lazy task/actor DAGs: build once with .bind(), execute many times.

Reference analog: python/ray/dag/ (dag_node.py:23 DAGNode;
function_node.py / class_node.py; input_node.py InputNode) — the
substrate under Serve deployment graphs.  `fn.bind(*args)` records a
node instead of submitting; `dag.execute(input)` walks the DAG,
submitting each task with its parents' ObjectRefs as arguments, so the
whole graph is in flight at once and intermediate values never pass
through the driver.

Shared-subexpression semantics match the reference: a node bound into
two downstream nodes executes ONCE per execute() call (results are
memoized per walk).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode"]


class DAGNode:
    """Base: a recorded, not-yet-submitted invocation."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- execution ---------------------------------------------------------

    def execute(self, *input_args, **input_kwargs):
        """Submit the whole DAG; returns this node's result handle
        (ObjectRef for task nodes, ActorHandle for class nodes)."""
        cache: Dict[int, Any] = {}
        return self._execute(cache, input_args, input_kwargs)

    def _resolve(self, value, cache, input_args, input_kwargs,
                 depth: int = 0):
        if isinstance(value, DAGNode):
            out = value._execute(cache, input_args, input_kwargs)
            if depth > 0:
                # refs nested inside containers are NOT auto-resolved by
                # the task layer (standard task-arg semantics), so the
                # DAG resolves them here; top-level refs pass through and
                # resolve worker-side with no driver round-trip
                from ray_tpu import ObjectRef, get

                if isinstance(out, ObjectRef):
                    out = get(out)
            return out
        if isinstance(value, (list, tuple)):
            return type(value)(
                self._resolve(v, cache, input_args, input_kwargs,
                              depth + 1)
                for v in value)
        if isinstance(value, dict):
            return {k: self._resolve(v, cache, input_args, input_kwargs,
                                     depth + 1)
                    for k, v in value.items()}
        return value

    def _execute(self, cache, input_args, input_kwargs):
        key = id(self)
        if key not in cache:
            # each actual argument resolves at depth 0: a node in
            # top-level position passes its ObjectRef straight into the
            # downstream .remote() call (worker-side resolution, graph
            # stays in flight); only container-nested refs are get()-ed
            args = tuple(
                self._resolve(a, cache, input_args, input_kwargs, 0)
                for a in self._bound_args)
            kwargs = {
                k: self._resolve(v, cache, input_args, input_kwargs, 0)
                for k, v in self._bound_kwargs.items()}
            cache[key] = self._submit(args, kwargs)
        return cache[key]

    def _submit(self, args, kwargs):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for execute()-time input (reference:
    dag/input_node.py).  Use as a context manager for parity with the
    reference API, or construct directly."""

    def __init__(self, index: int = 0, key: Optional[str] = None):
        super().__init__((), {})
        self._index = index
        self._key = key

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute(self, cache, input_args, input_kwargs):
        if self._key is not None:
            return input_kwargs[self._key]
        return input_args[self._index]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _submit(self, args, kwargs):
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor construction; method .bind() on it records method
    nodes against the (lazily created, per-execute) actor."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls

    def _submit(self, args, kwargs):
        return self._cls.remote(*args, **kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodBinder(self, name)


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args,
                               kwargs)


class ClassMethodNode(DAGNode):
    """The actor handle is just the node's first bound dependency, so
    the shared DAGNode._execute memoize/resolve path covers it (a
    ClassNode resolves to an ActorHandle, which passes through depth-0
    resolution untouched)."""

    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__((class_node,) + tuple(args), kwargs)
        self._method = method

    def _submit(self, args, kwargs):
        handle, *rest = args
        return getattr(handle, self._method).remote(*rest, **kwargs)
