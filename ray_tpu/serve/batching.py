"""@serve.batch: transparent request batching inside a replica.

Reference analog: python/ray/serve/batching.py (@serve.batch collects
concurrent calls into one vectorized invocation).  TPU rationale is
stronger than the reference's GPU one: a jitted model compiled for
batch N amortizes dispatch and fills the MXU, so the replica should see
lists, not single requests.

Usage (async methods only — batching needs an event loop to park
pending callers on):

    @serve.deployment
    class Model:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01)
        async def __call__(self, inputs: List[np.ndarray]):
            return model_apply(self.params, np.stack(inputs))

Each caller awaits its own element of the returned list.
"""

from __future__ import annotations

import asyncio
import functools
from dataclasses import dataclass
from typing import Any, Callable, List, Optional


@dataclass
class ChunkCursor:
    """Progress cursor for chunked streaming prefill (serve/llm.py):
    a queued long prompt is admitted once but filled over several
    block-aligned ``paged_prefill`` calls interleaved with decode
    waves, and the engine's slot record carries this cursor between
    waves.  ``filled`` counts prompt tokens already resident in KV
    blocks (including any reused prefix), so the next chunk's program
    call gets ``prefix_len == filled``."""

    total: int          # prompt length in tokens
    chunk_tokens: int   # scheduler budget per prefill turn
    filled: int = 0     # tokens already written to KV blocks
    chunks_done: int = 0

    @property
    def remaining(self) -> int:
        return self.total - self.filled

    @property
    def done(self) -> bool:
        return self.filled >= self.total

    def next_chunk(self) -> int:
        """Token count for the next prefill call (last one may be
        short)."""
        return min(self.chunk_tokens, self.remaining)

    def advance(self, n: int) -> None:
        self.filled += n
        self.chunks_done += 1


@dataclass
class HandoffCursor:
    """State of one disaggregated prefill→decode KV handoff
    (serve/llm.py + serve/router.py two-stage dispatch): a prefill
    replica that finishes a request's last chunk resolves its future
    with this cursor instead of generated tokens, and the router
    forwards it to the chosen decode replica, whose admission path
    installs the exported block rows and resumes decoding at
    ``first_token``.

    ``k_rows``/``v_rows`` are the filled KV block rows gathered by the
    prefill engine's ``kv_handoff_export`` program — jax device arrays
    on the same-process fast path, host numpy after the D2H hop on the
    staged path (``path`` records which).  ``meta`` carries the
    prefill-side telemetry timing (enqueue/admit/first-token/chunk
    windows) so the decode replica's record decomposes exactly like a
    monolithic engine's, plus the new ``handoff_ms`` leg."""

    prompt: Any                # np.int32 prompt token array
    first_token: int           # sampled at the prefill replica's last chunk
    n_tokens: int              # prompt tokens resident in the exported rows
    n_blocks: int              # filled block rows exported (leading rows)
    k_rows: Any = None         # stacked K rows, shape (maxn, L, bs, H, hd)
    v_rows: Any = None         # stacked V rows, same shape
    nbytes: int = 0            # payload footprint (both stacks)
    path: str = "fast"         # "fast" device copy | "staged" D2H→H2D
    t_export0: float = 0.0     # export dispatch start (prefill side)
    t_export1: float = 0.0     # export fence end (prefill side)
    installed: bool = False    # decode side flips this after the splice
    meta: Any = None           # telemetry meta for record_enqueue_handoff
    sampling: Any = None       # per-request SamplingParams override

    @property
    def done(self) -> bool:
        return self.installed


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch = max_batch_size
        self.timeout = batch_wait_timeout_s
        self._pending: List = []  # (arg, future)
        self._flush_task: Optional[asyncio.Task] = None

    async def submit(self, instance, arg):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((arg, fut))
        if len(self._pending) >= self.max_batch:
            self._flush(instance)
        elif self._flush_task is None or self._flush_task.done():
            self._flush_task = loop.create_task(
                self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self.timeout)
        self._flush(instance)

    def _flush(self, instance) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.cancel()
            self._flush_task = None
        asyncio.get_running_loop().create_task(
            self._run(instance, batch))

    async def _run(self, instance, batch) -> None:
        args = [a for a, _ in batch]
        futs = [f for _, f in batch]
        try:
            if instance is None:
                results = await self.fn(args)
            else:
                results = await self.fn(instance, args)
            if not isinstance(results, (list, tuple)) or \
                    len(results) != len(args):
                raise TypeError(
                    f"@serve.batch function must return a list of "
                    f"{len(args)} results (one per request), got "
                    f"{type(results).__name__}")
            for fut, res in zip(futs, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as e:  # noqa: BLE001 - propagate to every caller
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)


class RequestQueue:
    """FIFO admission queue for slot-based continuous batching
    (serve/llm.py): callers enqueue one request and await its future;
    the scheduler pops up to n pending requests whenever cache slots
    free up.  The complement of @serve.batch — that collects FIXED
    batches and runs them to completion, this hands out work as
    capacity appears mid-flight."""

    def __init__(self):
        self._pending: List = []  # (arg, future)

    def put(self, arg) -> "asyncio.Future":
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((arg, fut))
        return fut

    def pop(self, n: int) -> List:
        """Up to n oldest (arg, future) pairs, removed from the queue."""
        taken, self._pending = self._pending[:n], self._pending[n:]
        return taken

    def push_front(self, arg, fut) -> None:
        """Return a popped (arg, future) pair to the HEAD of the queue
        — used when admission pops a request but cannot place it yet
        (e.g. the KV block pool is exhausted until a retirement), so
        FIFO order survives the retry."""
        self._pending.insert(0, (arg, fut))

    def __len__(self) -> int:
        return len(self._pending)


class OverloadedError(Exception):
    """Raised to a caller whose request was load-shed at admission
    (AdmissionPolicy said the engine cannot meet its SLOs).  Callers
    should back off and retry; proxies map this to HTTP 503."""


class AdmissionPolicy:
    """SLO-driven load shedding: the control loop closing serve
    telemetry back into admission decisions.

    The continuous engine consults ``decide(stats, queue_depth)``
    before enqueueing each request, passing its own ``engine_stats()``
    snapshot.  A request is shed (reason string returned) when:

      * ``queue_depth >= max_queue_depth`` — backlog bound; or
      * observed p95 queue wait exceeds ``queue_wait_slo_ms`` while a
        backlog exists — admitted requests are already waiting longer
        than the SLO, so new ones cannot meet it; or
      * observed p95 TTFT exceeds ``ttft_slo_ms`` while a backlog
        exists; or
      * the kvscope HBM ledger's ``min_headroom_bytes`` (worst chip:
        bytes_limit − max(live allocator bytes, KV pool + audited
        program peak)) has fallen below ``min_headroom_bytes`` —
        admitting more work risks a device OOM, which no amount of
        queueing recovers from.

    The percentile gates only fire with a backlog (``queue_depth >
    0``): an idle engine with bad historical percentiles must accept
    work, or it could shed forever on stale history.  The headroom
    gate fires regardless of backlog — exhausted HBM does not heal by
    admitting the request that would exhaust it — but is inert when
    the ledger reports no measurable headroom (CPU backends, dense
    engines).  ``None`` for any threshold disables that gate; the
    default policy (all None except a generous queue bound) never
    sheds in small test runs."""

    def __init__(self, *, max_queue_depth: Optional[int] = None,
                 queue_wait_slo_ms: Optional[float] = None,
                 ttft_slo_ms: Optional[float] = None,
                 min_headroom_bytes: Optional[int] = None):
        self.max_queue_depth = max_queue_depth
        self.queue_wait_slo_ms = queue_wait_slo_ms
        self.ttft_slo_ms = ttft_slo_ms
        self.min_headroom_bytes = min_headroom_bytes

    def decide(self, stats, queue_depth: int) -> Optional[str]:
        """None = admit; otherwise the shed reason (metric label)."""
        if self.max_queue_depth is not None \
                and queue_depth >= self.max_queue_depth:
            return "queue_full"
        if self.min_headroom_bytes is not None:
            ledger = (stats.get("kv_scope") or {}).get("hbm_ledger") \
                or {}
            headroom = ledger.get("min_headroom_bytes")
            if headroom is not None \
                    and headroom < self.min_headroom_bytes:
                return "hbm_headroom"
        if queue_depth > 0:
            qw = (stats.get("queue_wait_ms") or {}).get("p95")
            if self.queue_wait_slo_ms is not None and qw is not None \
                    and qw > self.queue_wait_slo_ms:
                return "queue_wait_slo"
            ttft = (stats.get("ttft_ms") or {}).get("p95")
            if self.ttft_slo_ms is not None and ttft is not None \
                    and ttft > self.ttft_slo_ms:
                return "ttft_slo"
        return None

    def describe(self) -> dict:
        return {"max_queue_depth": self.max_queue_depth,
                "queue_wait_slo_ms": self.queue_wait_slo_ms,
                "ttft_slo_ms": self.ttft_slo_ms,
                "min_headroom_bytes": self.min_headroom_bytes}


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator turning `async def f(self, item)` call sites into
    batched `f(self, [items])` invocations (reference: serve.batch)."""

    def wrap(fn: Callable):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async function")
        # queue lives ON the instance (unique attr per decorated method):
        # an id()-keyed side table would leak queues and could alias a
        # recycled instance address to a dead instance's pending batch
        attr = f"__serve_batch_queue_{fn.__qualname__}"
        free_queue: List[Optional[_BatchQueue]] = [None]

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            if kwargs:
                raise TypeError("@serve.batch calls take one positional "
                                "argument")
            if len(args) == 2:       # bound method: (self, item)
                instance, item = args
            elif len(args) == 1:     # free function: (item,)
                instance, item = None, args[0]
            else:
                raise TypeError("@serve.batch function must take exactly "
                                "one request argument")
            if instance is None:
                q = free_queue[0]
                if q is None:
                    q = free_queue[0] = _BatchQueue(
                        fn, max_batch_size, batch_wait_timeout_s)
            else:
                q = getattr(instance, attr, None)
                if q is None:
                    q = _BatchQueue(fn, max_batch_size,
                                    batch_wait_timeout_s)
                    setattr(instance, attr, q)
            return await q.submit(instance, item)

        wrapper._ray_tpu_serve_batch = True
        return wrapper

    return wrap(_func) if _func is not None else wrap
