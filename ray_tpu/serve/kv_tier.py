"""kv_tier — tiered host-RAM KV cache (the second-chance store under
`BlockPager`'s LRU eviction).

HBM holds the hot working set of paged KV blocks; this module is the
warm tier behind it.  When the pager's LRU eviction claims a
registered prefix block, the engine copies that block's K/V rows
device→host and `put()`s them here under the SAME content-addressed
token-tuple key the prefix index uses — eviction becomes a D2H copy
instead of an erasure.  On a later admission whose HBM prefix match
falls short, the pager probes this store second-chance
(`BlockPager.tier_lookup`): a hit means the engine allocates fresh
block rows, installs the host copy via one H2D copy + block-table
splice, and bumps ``prefix_len`` so ``paged_prefill`` skips those
tokens exactly as it does for HBM-resident prefixes.  Content
addressing makes the restore bit-identical to a re-prefill by
construction — same tokens, same K/V rows — so outputs stay
bit-identical to the dense one-shot oracle.

The same move the Ray object store makes for objects (spill cold data
to a cheaper tier, restore on demand rather than recompute), applied
to KV blocks: the effective prefix cache grows far beyond HBM and a
re-admitted prefix costs one H2D copy instead of a full re-prefill
(kvscope's ``reprefill_waste_tokens`` is exactly the compute this
saves).

Division of labor:

  * the TIER (this module) is a byte-budgeted, LRU-evicting host
    store — pure bookkeeping over numpy arrays, no device access,
    no clocks (graftcheck's `wallclock-in-telemetry` rule covers this
    file; the engine feeds measured copy seconds into
    ``note_h2d``/``note_d2h``, trainwatch-style);
  * the PAGER decides WHEN to spill (its eviction path) and WHAT to
    restore (its second-chance lookup), and keeps the scope/journal
    accounting honest — a tier restore books ``tier_hits`` /
    ``tokens_restored``, never ``reprefill_waste_tokens``;
  * the ENGINE owns every device copy: its block-saver callback
    gathers a block's K/V rows to host at spill time, and its jitted
    ``install_blocks`` program splices a restored chain back into the
    pool in one fixed-shape dispatch (on sharded engines the H2D
    transfer re-distributes the replicated host rows under the
    cache's shardings).
"""

from __future__ import annotations

import collections
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["HostKVTier", "empty_kv_tier", "staging_buffers"]


def staging_buffers(maxn: int, row_shape: Tuple[int, ...],
                    dtype) -> Tuple[np.ndarray, np.ndarray,
                                    np.ndarray]:
    """Persistent host staging triple ``(ids, k_rows, v_rows)`` for
    fixed-shape block-splice dispatches: the tier restore path and the
    disaggregated handoff's staged D2H→H2D hop (serve/llm.py) both
    refill these in place per transfer instead of re-allocating pad
    arrays.  ``maxn`` is the id-vector length (max_seq // block_size)
    and ``row_shape`` the stacked per-block row shape the engine's
    install program expects."""
    return (np.zeros((maxn,), np.int32),
            np.zeros(row_shape, dtype),
            np.zeros(row_shape, dtype))

#: one stored block: per-layer K rows, per-layer V rows (host numpy,
#: shape (n_layer, block_size, kv_heads, head_dim)), byte footprint
Entry = Dict[str, object]


class HostKVTier:
    """Byte-budgeted LRU host store of evicted KV blocks, keyed by
    the pager's content-addressed prefix keys (exact token tuples —
    no hash collisions, so a restored block can never be wrong
    content).

    ``put`` spills one block (evicting least-recently-used entries
    until the budget fits; an entry larger than the whole budget is
    dropped on the floor rather than thrashing the store), ``take``
    is the counted second-chance probe, and the ``note_*`` hooks
    absorb engine-measured copy seconds so ``stats()`` can report
    h2d/d2h cost without this module ever reading a clock.
    """

    def __init__(self, bytes_budget: int):
        if int(bytes_budget) <= 0:
            raise ValueError(
                f"bytes_budget={bytes_budget} must be positive")
        self.bytes_budget = int(bytes_budget)
        #: key -> {"k": np, "v": np, "bytes": int}; insertion order ==
        #: LRU order (put/take both move-to-end)
        self._store: "collections.OrderedDict[Tuple[int, ...], Entry]" \
            = collections.OrderedDict()
        self.bytes_resident = 0
        self.hits = 0          # take() probes that found the key
        self.misses = 0        # take() probes that came up empty
        self.saves = 0         # blocks spilled in (D2H copies)
        self.evictions = 0     # entries LRU-dropped to fit the budget
        self.tokens_restored = 0  # token slots re-admitted via H2D
        # engine-fed copy time (seconds accumulate, stats reports ms)
        self._h2d_s = 0.0
        self._d2h_s = 0.0

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, key: Tuple[int, ...]) -> bool:
        return key in self._store

    # -- spill / restore -----------------------------------------------

    def put(self, key: Tuple[int, ...], k_rows, v_rows) -> int:
        """Spill one evicted block's host K/V rows under `key`.
        Returns the bytes now resident for the key (0 when the entry
        alone exceeds the whole budget and was skipped).  Re-putting a
        resident key refreshes its rows and its LRU position."""
        nbytes = int(k_rows.nbytes) + int(v_rows.nbytes)
        if nbytes > self.bytes_budget:
            return 0
        old = self._store.pop(key, None)
        if old is not None:
            self.bytes_resident -= int(old["bytes"])
        while self._store and \
                self.bytes_resident + nbytes > self.bytes_budget:
            _, victim = self._store.popitem(last=False)   # LRU
            self.bytes_resident -= int(victim["bytes"])
            self.evictions += 1
        self._store[key] = {"k": k_rows, "v": v_rows, "bytes": nbytes}
        self.bytes_resident += nbytes
        self.saves += 1
        return nbytes

    def refresh(self, key: Tuple[int, ...]) -> int:
        """LRU-touch `key` if resident; returns its byte footprint
        (0 when absent).  The pager's eviction path calls this FIRST:
        content addressing makes the rows under a key immutable, so
        when the key is already resident the D2H gather would copy
        bit-identical bytes — the spill becomes a free LRU refresh.
        Not a probe (take() counts hit/miss) and not a save (no copy
        happened), so the counters stay honest."""
        if key not in self._store:
            return 0
        self._store.move_to_end(key)
        return int(self._store[key]["bytes"])

    def take(self, key: Tuple[int, ...]) -> Optional[Entry]:
        """Second-chance probe: the entry for `key`, or None.  A hit
        refreshes the entry's LRU position but KEEPS it resident —
        the tier is a cache, and the same prefix can be evicted from
        HBM and restored again later."""
        entry = self._store.get(key)
        if entry is None:
            self.misses += 1
            return None
        self._store.move_to_end(key)
        self.hits += 1
        return entry

    # -- engine-fed accounting -----------------------------------------

    def note_restored(self, tokens: int) -> None:
        """The pager registered tier-restored blocks covering
        `tokens` token slots — prefill work the tier just saved."""
        self.tokens_restored += int(tokens)

    def note_h2d(self, seconds: float) -> None:
        """Engine-measured restore (host→device install) seconds."""
        self._h2d_s += max(0.0, float(seconds))

    def note_d2h(self, seconds: float) -> None:
        """Engine-measured spill (device→host gather) seconds."""
        self._d2h_s += max(0.0, float(seconds))

    # -- introspection -------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """The ``kv_tier`` block of ``engine_stats()`` (shape pinned
        by test_engine_stats_schema; `empty_kv_tier` is the zeroed
        twin engines without a tier report)."""
        probes = self.hits + self.misses
        return {
            "enabled": True,
            "bytes_budget": self.bytes_budget,
            "bytes_resident": self.bytes_resident,
            "entries": len(self._store),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / probes, 4) if probes
            else 0.0,
            "saves": self.saves,
            "evictions": self.evictions,
            "tokens_restored": self.tokens_restored,
            "h2d_ms": round(self._h2d_s * 1e3, 3),
            "d2h_ms": round(self._d2h_s * 1e3, 3),
        }


def empty_kv_tier() -> Dict[str, object]:
    """The stable zero-shaped ``kv_tier`` block engines WITHOUT a
    host tier report (dense layouts, paged with the knob unset) —
    same keys as a live tier so dashboards, fleet pooling, and the
    golden-schema test never branch on configuration."""
    return {
        "enabled": False,
        "bytes_budget": 0,
        "bytes_resident": 0,
        "entries": 0,
        "hits": 0,
        "misses": 0,
        "hit_rate": 0.0,
        "saves": 0,
        "evictions": 0,
        "tokens_restored": 0,
        "h2d_ms": 0.0,
        "d2h_ms": 0.0,
    }
