"""HTTP ingress: an aiohttp server inside an actor, routing requests to
deployment replicas via DeploymentHandles.

Reference analog: serve/_private/http_proxy.py:189,333 HTTPProxyActor
(uvicorn ASGI there; aiohttp here — same role: per-node ingress that
forwards to replicas and never holds business logic).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional


class HTTPProxyActor:
    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 8000):
        self._controller = controller
        self.host = host
        self.port = port
        self._handles: Dict[str, Any] = {}
        self._routes: Dict[str, str] = {}
        self._routes_at = 0.0
        self._routes_ttl = 2.0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="http_proxy")
        self._thread.start()
        self._started.wait(timeout=30)

    def _refresh_routes(self):
        """Blocking controller round trip — call off the event loop."""
        import time

        import ray_tpu

        if time.monotonic() - self._routes_at < self._routes_ttl:
            return
        table = ray_tpu.get(
            self._controller.get_routing_table.remote(), timeout=30)
        self._routes = table["routes"]
        self._routes_at = time.monotonic()

    def _handle_for(self, deployment: str):
        from ray_tpu.serve.handle import DeploymentHandle

        if deployment not in self._handles:
            self._handles[deployment] = DeploymentHandle(
                deployment, self._controller)
        return self._handles[deployment]

    def _serve(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def dispatch(request: "web.Request") -> "web.Response":
            path = "/" + request.match_info.get("tail", "")
            await loop.run_in_executor(None, self._refresh_routes)
            target = None
            for prefix, dep in sorted(self._routes.items(),
                                      key=lambda kv: -len(kv[0])):
                if path == prefix or path.startswith(
                        prefix.rstrip("/") + "/") or prefix == "/":
                    target = dep
                    break
            if target is None:
                return web.json_response(
                    {"error": f"no route for {path}"}, status=404)
            if request.can_read_body:
                try:
                    payload = await request.json()
                except Exception:  # noqa: BLE001
                    payload = (await request.read()).decode()
            else:
                payload = dict(request.query) or None
            handle = self._handle_for(target)
            try:
                result = await loop.run_in_executor(
                    None, lambda: handle.call(payload, timeout=60))
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": repr(e)}, status=500)
            if isinstance(result, (dict, list, str, int, float, bool,
                                   type(None))):
                return web.json_response({"result": result})
            return web.json_response({"result": repr(result)})

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", dispatch)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        self._started.set()
        loop.run_forever()

    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def ping(self) -> bool:
        return self._started.is_set()
