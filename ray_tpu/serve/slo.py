"""SLO burn-rate engine for the serve telemetry stream.

``EngineTelemetry`` measures; this module JUDGES.  A per-deployment
:class:`SLOConfig` names latency targets (TTFT, end-to-end, queue
wait) and an objective ("99% of requests inside the target"), and
:class:`SLOTracker` turns the telemetry stream into multi-window
**burn rates** — the SRE error-budget idiom:

    burn_rate = observed_violation_rate / (1 - objective)

A burn rate of 1.0 means the deployment is consuming its error budget
exactly as fast as the objective allows; above 1.0 it will miss the
SLO if the window's behaviour persists.  Computing the same rate over
a short AND a long window (default 30 s / 300 s) keeps the signal both
fast (the short window trips within seconds of a regression) and
de-noised (the long window confirms it is not a blip).

The tracker is also the **anomaly watchdog**: ``check()`` runs from
the engine loop (throttled), and on a burn-rate breach transition or a
recompile-storm trip (``device_stats`` registry subscription) it dumps
the flight recorder's journal (``_private/flightrec.py``) to a
postmortem file — the "what was the engine doing" answer — and can
opt-in trigger a ``profile_device`` capture.  Everything it computes
is exposed three ways: ``engine_stats()["slo"]``, ``serve_slo_*``
Prometheus metrics, and the dashboard's ``GET /api/serve/slo``.

Clock discipline matches telemetry: monotonic ``perf_counter`` only,
``now`` injectable for deterministic tests (enforced by graftcheck's
``wallclock-in-telemetry`` rule, which covers this file).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["SLOConfig", "SLOTracker", "worst_burn_rate"]


def worst_burn_rate(slo_block) -> float:
    """Max burn rate across objectives in an ``SLOTracker.snapshot()``
    / ``engine_stats()["slo"]`` block — the scalar the fleet autoscaler
    (serve/router.py) and the controller's "burn_rate" load signal
    consume.  0.0 for engines without an SLO config (None block) or
    malformed blocks, so callers can feed it unconditionally."""
    if not isinstance(slo_block, dict):
        return 0.0
    worst = 0.0
    for obj in (slo_block.get("objectives") or {}).values():
        try:
            worst = max(worst, float(obj.get("burn_rate", 0.0)))
        except (TypeError, ValueError):
            continue
    return worst

_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None


def _slo_metrics() -> Dict[str, Any]:
    """Process-wide serve_slo_* metric singletons (same pattern as
    serve/telemetry.py — one registration per name however many
    deployments this process hosts)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = {
                "burn_rate": Gauge(
                    "serve_slo_burn_rate",
                    "error-budget burn rate per objective and window "
                    "(>1 = missing the SLO at this pace)",
                    tag_keys=("deployment", "objective", "window")),
                "attainment": Gauge(
                    "serve_slo_attainment",
                    "fraction of retained requests inside the "
                    "objective's latency target",
                    tag_keys=("deployment", "objective")),
                "breaches": Counter(
                    "serve_slo_breaches_total",
                    "burn-rate breach transitions per objective",
                    tag_keys=("deployment", "objective")),
                "dumps": Counter(
                    "serve_flightrec_dumps_total",
                    "postmortem flight-record dumps, by trigger",
                    tag_keys=("deployment", "trigger")),
            }
        return _metrics


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Latency SLOs for one deployment.

    Targets are milliseconds; a ``None`` target disables that
    objective.  ``objective`` is the success fraction the SLO promises
    (0.99 → a 1% error budget) and ``windows_s`` the burn-rate
    windows.  An objective breaches when its burn rate exceeds
    ``burn_threshold`` in any window holding at least ``min_samples``
    samples; on the False→True transition the watchdog dumps the
    flight record into ``dump_dir`` (default: the recorder's own,
    see flightrec.default_dump_dir) and, when ``profile_on_breach``,
    holds a ``profile_device`` capture for ``profile_seconds`` —
    capture blocks the engine loop for that long, so it is strictly
    opt-in.  ``check_interval_s`` throttles the watchdog; ``max_dumps``
    caps postmortem files per tracker so a flapping SLO cannot fill a
    disk."""

    ttft_ms: Optional[float] = None
    e2e_ms: Optional[float] = None
    queue_wait_ms: Optional[float] = None
    objective: float = 0.99
    windows_s: Tuple[float, ...] = (30.0, 300.0)
    burn_threshold: float = 1.0
    min_samples: int = 1
    check_interval_s: float = 0.25
    dump_on_breach: bool = True
    dump_dir: Optional[str] = None
    max_dumps: int = 8
    profile_on_breach: bool = False
    profile_logdir: Optional[str] = None
    profile_seconds: float = 0.5

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")
        if not self.windows_s or any(w <= 0 for w in self.windows_s):
            raise ValueError(
                f"windows_s must be positive, got {self.windows_s}")
        if self.burn_threshold <= 0:
            raise ValueError("burn_threshold must be > 0")
        if self.min_samples < 1:
            raise ValueError("min_samples must be >= 1")
        for name, v in (("ttft_ms", self.ttft_ms),
                        ("e2e_ms", self.e2e_ms),
                        ("queue_wait_ms", self.queue_wait_ms)):
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be > 0, got {v}")

    def objectives(self) -> Dict[str, float]:
        """objective name -> target_ms, configured entries only."""
        out = {}
        if self.ttft_ms is not None:
            out["ttft"] = float(self.ttft_ms)
        if self.e2e_ms is not None:
            out["e2e"] = float(self.e2e_ms)
        if self.queue_wait_ms is not None:
            out["queue_wait"] = float(self.queue_wait_ms)
        return out


class SLOTracker:
    """Burn-rate computation + anomaly watchdog over one engine's
    telemetry.  Created by the continuous engine when an ``SLOConfig``
    is passed; ``snapshot()`` is the pure read (engine_stats/
    dashboard), ``check()`` the throttled watchdog the engine loop
    drives after each step."""

    def __init__(self, config: SLOConfig, telemetry,
                 recorder=None):
        self.config = config
        self.deployment = telemetry.deployment
        self._telemetry = telemetry
        self._recorder = recorder
        if recorder is not None and config.dump_dir is not None:
            recorder.dump_dir = config.dump_dir
        self._m = _slo_metrics()
        self._lock = threading.Lock()
        self._last_check: Optional[float] = None
        self._breached: Dict[str, bool] = {}
        self._storms: List[str] = []
        self.breaches = 0
        self.dumps: List[str] = []

    # -- storm subscription (device_stats registry) --------------------

    def note_storm(self, program: str) -> None:
        """A recompile storm tripped; the next ``check()`` dumps."""
        with self._lock:
            self._storms.append(program)

    # -- burn rates ----------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``engine_stats()["slo"]`` block: per-objective overall
        attainment plus per-window violation counts and burn rates."""
        now = time.perf_counter() if now is None else now
        cfg = self.config
        budget = 1.0 - cfg.objective
        samples = self._telemetry.slo_samples()
        objectives: Dict[str, Any] = {}
        for name, target in cfg.objectives().items():
            series = samples.get(name, [])
            total = len(series)
            viol = sum(1 for _ts, v in series if v > target)
            windows: Dict[str, Any] = {}
            worst = 0.0
            breached = False
            for w in cfg.windows_s:
                vals = [v for ts, v in series if now - ts <= w]
                n = len(vals)
                bad = sum(1 for v in vals if v > target)
                err = bad / n if n else 0.0
                burn = err / budget
                windows[f"{w:g}s"] = {
                    "samples": n, "violations": bad,
                    "attainment": round(1.0 - err, 4),
                    "burn_rate": round(burn, 3),
                }
                if n >= cfg.min_samples:
                    worst = max(worst, burn)
                    if burn > cfg.burn_threshold:
                        breached = True
            objectives[name] = {
                "target_ms": target,
                "samples": total,
                "violations": viol,
                "attainment": round(1.0 - viol / total, 4)
                if total else None,
                "burn_rate": round(worst, 3),
                "breached": breached,
                "windows": windows,
            }
        with self._lock:
            breaches = self.breaches
            dumps = list(self.dumps)
        return {
            "config": {
                "objective": cfg.objective,
                "windows_s": list(cfg.windows_s),
                "burn_threshold": cfg.burn_threshold,
                "targets_ms": cfg.objectives(),
            },
            "objectives": objectives,
            "breached": any(o["breached"]
                            for o in objectives.values()),
            "breaches": breaches,
            "dumps": dumps,
        }

    # -- watchdog ------------------------------------------------------

    def check(self, now: Optional[float] = None
              ) -> Optional[Dict[str, Any]]:
        """Throttled watchdog pass: recompute burn rates, publish the
        serve_slo_* gauges, and on a fresh breach (or a queued
        recompile storm) postmortem-dump the flight record.  Returns
        the snapshot when a pass ran, None when throttled."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            if self._last_check is not None and \
                    now - self._last_check < self.config.check_interval_s:
                return None
            self._last_check = now
            storms, self._storms = self._storms, []
        snap = self.snapshot(now)
        tags = {"deployment": self.deployment}
        for name, obj in snap["objectives"].items():
            otags = dict(tags, objective=name)
            if obj["attainment"] is not None:
                self._m["attainment"].set(obj["attainment"],
                                          tags=otags)
            for win, blk in obj["windows"].items():
                self._m["burn_rate"].set(
                    blk["burn_rate"], tags=dict(otags, window=win))
            fresh = obj["breached"] and not self._breached.get(name)
            cleared = (not obj["breached"]
                       and self._breached.get(name))
            self._breached[name] = obj["breached"]
            if cleared and self._recorder is not None:
                # close the burn window: incidents.py pairs this with
                # the opening slo_breach to bound the incident span
                self._recorder.record(
                    "slo_recover", objective=name,
                    burn_rate=obj["burn_rate"],
                    target_ms=obj["target_ms"])
            if fresh:
                with self._lock:
                    self.breaches += 1
                self._m["breaches"].inc(tags=otags)
                if self._recorder is not None:
                    self._recorder.record(
                        "slo_breach", objective=name,
                        burn_rate=obj["burn_rate"],
                        target_ms=obj["target_ms"])
                self._dump(f"slo_breach_{name}",
                           {"slo": snap, "objective": name})
                self._profile()
        for program in storms:
            self._dump("recompile_storm", {"program": program,
                                           "slo": snap})
        snap["breaches"] = self.breaches
        with self._lock:
            snap["dumps"] = list(self.dumps)
        return snap

    def _dump(self, trigger: str, context: Dict[str, Any]) -> None:
        if self._recorder is None or not self.config.dump_on_breach:
            return
        with self._lock:
            if len(self.dumps) >= self.config.max_dumps:
                return
        try:
            path = self._recorder.dump(reason=trigger, context=context)
        except Exception:  # noqa: BLE001 - watchdog must not kill the engine
            return
        if path is None:
            return
        with self._lock:
            self.dumps.append(path)
        self._m["dumps"].inc(tags={"deployment": self.deployment,
                                   "trigger": trigger})

    def _profile(self) -> None:
        """Opt-in breach capture: hold a ``profile_device`` window.
        Deliberately synchronous — it blocks the engine loop for
        ``profile_seconds``, which is why it defaults off."""
        if not self.config.profile_on_breach:
            return
        try:
            from ray_tpu.util.state import profile_device

            logdir = self.config.profile_logdir or \
                (self._recorder.dump_dir if self._recorder is not None
                 and self._recorder.dump_dir else None)
            from ray_tpu._private.flightrec import default_dump_dir
            with profile_device(logdir or default_dump_dir()):
                time.sleep(self.config.profile_seconds)
        except Exception:  # noqa: BLE001 - capture is best-effort
            pass
