"""Model serving on actors (reference analog: python/ray/serve/)."""

from ray_tpu.serve.api import (Deployment, delete, deployment,
                               engine_stats, get_deployment_handle,
                               run, shutdown, start_http_proxy, status)
from ray_tpu.serve.batching import (AdmissionPolicy, OverloadedError,
                                    batch)
from ray_tpu.serve.kv_pager import BlockPager
from ray_tpu.serve.kv_tier import HostKVTier
from ray_tpu.serve.llm import (SamplingParams, SpecConfig,
                               build_llm_deployment)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.schema import (DeploymentSchema,
                                  ServeApplicationSchema)
from ray_tpu.serve.router import (AutoscalePolicy, LLMFleet,
                                  LLMRouter, TenantClass,
                                  build_llm_fleet)
from ray_tpu.serve.schema import apply as apply_config
from ray_tpu.serve.slo import SLOConfig, worst_burn_rate
from ray_tpu.serve.traffic import (TenantSpec, TrafficGenerator,
                                   TrafficSpec, run_traffic,
                                   run_traffic_fleet)

__all__ = ["deployment", "Deployment", "run", "delete", "shutdown",
           "DeploymentHandle", "get_deployment_handle",
           "start_http_proxy", "batch", "status", "engine_stats",
           "ServeApplicationSchema", "DeploymentSchema",
           "apply_config", "build_llm_deployment", "AdmissionPolicy",
           "OverloadedError", "BlockPager", "HostKVTier",
           "TrafficSpec",
           "TrafficGenerator", "run_traffic", "SamplingParams",
           "SpecConfig", "SLOConfig", "worst_burn_rate",
           "TenantSpec", "TenantClass", "AutoscalePolicy",
           "LLMRouter", "LLMFleet", "build_llm_fleet",
           "run_traffic_fleet"]
