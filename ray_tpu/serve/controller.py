"""ServeController: the serve control plane, one named detached actor.

Reference analog: serve/controller.py:61 ServeController (:410
deploy_app) + _private/deployment_state.py reconciliation.  Owns desired
deployment state, creates/updates replica actors, repairs dead replicas
(background reconcile thread), and hands routing tables to handles —
the pull-based stand-in for the reference's LongPollHost push channel
(serve/_private/long_poll.py:184).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


def _load_value(v: Any) -> float:
    """Per-replica ongoing load from a probe result: probes historically
    return a bare pending count (float); richer probes return a dict
    carrying at least {"pending": ...}.  Both shapes are accepted
    everywhere loads are consumed so signals can evolve without
    breaking the controller."""
    if isinstance(v, dict):
        return float(v.get("pending", 0.0))
    return float(v)


def _queue_depth_signal(loads: Dict[Any, Any],
                        ac: Dict[str, Any]) -> float:
    """LEGACY DEFAULT load signal: total ongoing requests across
    replicas (the reconcile probe's pending counts).  This is the
    reference autoscaling_policy behavior — desired replicas =
    ceil(total / target_ongoing_requests) — and what every deployment
    gets unless its autoscaling config names another signal."""
    return sum(_load_value(v) for v in loads.values())


def _burn_rate_signal(loads: Dict[Any, Any],
                      ac: Dict[str, Any]) -> float:
    """SLO-aware load signal: queue depth inflated by burn rate.  A
    replica burning its error budget at b× the configured
    ``burn_threshold`` counts as b× its pending load (never less than
    its raw pending), so a fleet meeting SLOs scales exactly like the
    legacy signal while a breaching fleet scales up even at modest
    queue depth.  Probe values must be dicts carrying "burn_rate"
    (worst objective, 30s window — see serve/slo.py worst_burn_rate);
    bare floats degrade to the legacy behavior."""
    threshold = float(ac.get("burn_threshold", 1.0)) or 1.0
    total = 0.0
    for v in loads.values():
        pending = _load_value(v)
        burn = float(v.get("burn_rate", 0.0)) if isinstance(v, dict) \
            else 0.0
        total += pending * max(1.0, burn / threshold)
    return total


#: Pluggable autoscaling load signals, selected per deployment via
#: ``autoscaling_config={"load_signal": "<name>", ...}``.  Values map a
#: per-replica loads dict (probe results) + the autoscaling config to
#: ONE total-load float that feeds desired = ceil(total / target).
#: The in-process fleet autoscaler (serve/router.py) routes its
#: burn-rate decisions through the same "burn_rate" entry.
LOAD_SIGNALS = {
    "queue_depth": _queue_depth_signal,
    "burn_rate": _burn_rate_signal,
}


class ServeController:
    def __init__(self):
        # name -> {config, replicas: [ActorHandle], version}
        self.deployments: Dict[str, Dict[str, Any]] = {}
        self.routes: Dict[str, str] = {}  # route_prefix -> deployment
        self._lock = threading.Lock()
        #: long-poll push channel (reference: serve/_private/long_poll.py
        #: :184 LongPollHost): every replica-set mutation bumps the
        #: deployment version and wakes blocked listen_for_change calls,
        #: so handles learn of changes push-style instead of on a poll
        #: interval.
        self._change = threading.Condition(self._lock)
        self._stop = False
        #: health checks (reference DeploymentConfig defaults:
        #: health_check_timeout_s=30, failure threshold 3)
        self._probe_timeout_s = 30.0
        self._probe_failure_threshold = 3
        self._probe_failures: Dict[Any, int] = {}
        self._last_loads: Dict[Any, float] = {}
        self._reconciler = threading.Thread(target=self._reconcile_loop,
                                            daemon=True,
                                            name="serve_reconcile")
        self._reconciler.start()

    def _bump_locked(self, name: str) -> None:
        """Caller holds self._lock: record a replica-set change and wake
        long-poll listeners."""
        dep = self.deployments.get(name)
        if dep is not None:
            dep["version"] += 1
        self._change.notify_all()

    # -- deploy path ------------------------------------------------------
    def deploy(self, name: str, serialized_def: bytes, init_args: tuple,
               init_kwargs: Dict[str, Any], *, num_replicas: int = 1,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               max_concurrent_queries: int = 8,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               route_prefix: Optional[str] = None) -> bool:
        if autoscaling_config:
            ac = {"min_replicas": 1, "max_replicas": 8,
                  "target_ongoing_requests": 2.0,
                  "upscale_delay_s": 0.0, "downscale_delay_s": 10.0}
            ac.update(autoscaling_config)
            num_replicas = max(num_replicas, ac["min_replicas"])
        else:
            ac = None
        with self._lock:
            old = self.deployments.get(name)
            cfg = {"serialized_def": serialized_def,
                   "init_args": init_args, "init_kwargs": init_kwargs,
                   "num_replicas": num_replicas,
                   "actor_options": ray_actor_options or {},
                   "max_concurrent_queries": max_concurrent_queries,
                   "autoscaling": ac}
            version = (old["version"] + 1) if old else 1
            replicas = [self._start_replica(name, cfg)
                        for _ in range(num_replicas)]
            self.deployments[name] = {"config": cfg, "replicas": replicas,
                                      "version": version,
                                      "scale_pending_since": None}
            self._change.notify_all()
            if route_prefix:
                self.routes[route_prefix] = name
            if old:
                for r in old["replicas"]:
                    self._kill_replica(r)
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            dep = self.deployments.pop(name, None)
            self.routes = {p: d for p, d in self.routes.items()
                           if d != name}
            self._change.notify_all()
        if dep:
            for r in dep["replicas"]:
                self._kill_replica(r)
        return dep is not None

    def _start_replica(self, name: str, cfg: Dict[str, Any]):
        import ray_tpu
        from ray_tpu.serve.replica import RayServeReplica

        opts = dict(cfg["actor_options"])
        opts.setdefault("num_cpus", 0.1)
        opts["max_concurrency"] = cfg["max_concurrent_queries"]
        return ray_tpu.remote(**opts)(RayServeReplica).remote(
            cfg["serialized_def"], cfg["init_args"], cfg["init_kwargs"],
            name)

    def _kill_replica(self, replica) -> None:
        import ray_tpu

        try:
            ray_tpu.kill(replica)
        except Exception:  # noqa: BLE001
            pass

    # -- routing ----------------------------------------------------------
    def get_replicas(self, name: str) -> List:
        with self._lock:
            dep = self.deployments.get(name)
            return list(dep["replicas"]) if dep else []

    def listen_for_change(self, name: str, known_version: int,
                          timeout: float = 25.0) -> Dict[str, Any]:
        """Long-poll push channel (reference:
        serve/_private/long_poll.py:184 LongPollHost.listen_for_change):
        blocks until the deployment's replica set differs from the
        caller's ``known_version`` (returning immediately when it
        already does), or until ``timeout`` — the caller re-issues the
        call in a loop, so membership changes propagate push-style with
        no polling interval.  A deleted deployment answers version -1.
        Runs on one of the controller actor's concurrency slots; the
        slot parks in Condition.wait, costing a thread but no CPU.
        Slots are BOUNDED: past ~100 parked listeners the call answers
        immediately with a backoff hint instead of parking, so
        control-plane calls (deploy/delete/status) never queue behind a
        wall of long-polls (the remaining concurrency slots stay
        free)."""
        deadline = time.monotonic() + timeout
        with self._change:
            dep = self.deployments.get(name)
            if dep is None:
                return {"version": -1, "replicas": []}
            if dep["version"] != known_version:
                return {"version": dep["version"],
                        "replicas": list(dep["replicas"])}
            if getattr(self, "_parked", 0) >= 100:
                # saturated: answer now with a backoff hint rather than
                # consuming one of the few remaining slots
                return {"version": known_version, "replicas": None,
                        "backoff": True}
            self._parked = getattr(self, "_parked", 0) + 1
            try:
                while True:
                    dep = self.deployments.get(name)
                    if dep is None:
                        return {"version": -1, "replicas": []}
                    if dep["version"] != known_version:
                        return {"version": dep["version"],
                                "replicas": list(dep["replicas"])}
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return {"version": known_version,
                                "replicas": None}
                    self._change.wait(remaining)
            finally:
                self._parked -= 1

    def get_routing_table(self) -> Dict[str, Any]:
        with self._lock:
            return {"routes": dict(self.routes),
                    "versions": {n: d["version"]
                                 for n, d in self.deployments.items()}}

    def list_deployments(self) -> List[str]:
        with self._lock:
            return sorted(self.deployments)

    def status(self) -> Dict[str, Any]:
        """Deployment statuses (reference: serve.status() /
        StatusOverview): replica counts, autoscaling mode, route."""
        with self._lock:
            out = {}
            routes = {v: k for k, v in self.routes.items()}
            for name, d in self.deployments.items():
                out[name] = {
                    "status": "HEALTHY" if d["replicas"] else "UNHEALTHY",
                    "replicas": len(d["replicas"]),
                    "target_replicas": d["config"].get("num_replicas",
                                                       len(d["replicas"])),
                    "autoscaling": bool(
                        d["config"].get("autoscaling_config")),
                    "version": d["version"],
                    "route": routes.get(name),
                }
            return out

    # -- reconciliation ---------------------------------------------------
    def configure_health_checks(self, *, probe_timeout_s: float = None,
                                failure_threshold: int = None) -> None:
        """Tune replica health probing (ops/tests; reference analog:
        DeploymentConfig health_check_timeout_s / failure threshold)."""
        if probe_timeout_s is not None:
            self._probe_timeout_s = float(probe_timeout_s)
        if failure_threshold is not None:
            self._probe_failure_threshold = int(failure_threshold)

    def _reconcile_loop(self):
        import ray_tpu

        while not self._stop:
            time.sleep(2.0)
            with self._lock:
                deps = {n: list(d["replicas"])
                        for n, d in self.deployments.items()}
            for name, replicas in deps.items():
                loads: Dict[Any, float] = {}
                # Out-of-band probes: liveness + queue depth in one
                # call, answered on the worker's server loop so a
                # replica saturated with user requests still reports
                # (reference: health checks on the control concurrency
                # group).  All probes go out CONCURRENTLY under one
                # deadline — a single wedged replica must not stall
                # health checks for everything else by timeout×N.
                refs = [(r, r.raytpu_probe.remote()) for r in replicas]
                deadline = time.monotonic() + self._probe_timeout_s
                for r, ref in refs:
                    try:
                        info = ray_tpu.get(
                            ref, timeout=max(
                                0.1, deadline - time.monotonic()))
                        loads[r] = float(info.get("pending", 0))
                        self._probe_failures.pop(r, None)
                        self._last_loads[r] = loads[r]
                    except Exception:  # noqa: BLE001 - maybe dead
                        # Replacement needs CONSECUTIVE failures
                        # (reference: health_check_failure_threshold):
                        # a replica mid-jit-trace can hold the GIL past
                        # one probe window without being dead — tearing
                        # it down also throws away its warm compile
                        # cache and any replica state.  Keyed by the
                        # handle itself (held reference → stable id),
                        # pruned below when replicas leave.
                        n = self._probe_failures.get(r, 0) + 1
                        self._probe_failures[r] = n
                        if n < self._probe_failure_threshold:
                            # still routed + autoscale-visible: carry
                            # the last-known load (default 1.0) so a
                            # busy-but-unprobed replica is neither a
                            # preferred downscale victim (0.0 would
                            # sort it first) nor an upscale trigger
                            loads[r] = self._last_loads.get(r, 1.0)
                            continue
                        self._probe_failures.pop(r, None)
                        with self._lock:
                            dep = self.deployments.get(name)
                            if dep is None or r not in dep["replicas"]:
                                continue
                            dep["replicas"].remove(r)
                            try:
                                dep["replicas"].append(
                                    self._start_replica(name,
                                                        dep["config"]))
                            except Exception:  # noqa: BLE001
                                pass
                            self._bump_locked(name)
                self._autoscale_one(name, loads)
            # prune bookkeeping for replicas no longer deployed
            with self._lock:
                live = {r for d in self.deployments.values()
                        for r in d["replicas"]}
            for table in (self._probe_failures, self._last_loads):
                for r in list(table):
                    if r not in live:
                        table.pop(r, None)

    def _autoscale_one(self, name: str,
                       loads: Optional[Dict[Any, float]] = None) -> None:
        """Replica scaling from a PLUGGABLE load signal (reference:
        autoscaling_policy.py:93 calculate_desired_num_replicas — desired
        = ceil(total_load / target) — and :127's upscale/downscale delay
        smoothing).  ``loads``: per-replica probe results — bare pending
        counts (legacy) or dicts with "pending" and optionally
        "burn_rate".  The signal is chosen by the deployment's
        ``autoscaling_config["load_signal"]`` from LOAD_SIGNALS;
        the default "queue_depth" reproduces the historical raw-queue-
        length behavior exactly, so deployments that don't opt in see
        no change."""
        import math

        with self._lock:
            dep = self.deployments.get(name)
            if dep is None or not dep["config"].get("autoscaling"):
                return
            ac = dep["config"]["autoscaling"]
        signal = LOAD_SIGNALS.get(str(ac.get("load_signal",
                                             "queue_depth")),
                                  _queue_depth_signal)
        total = signal(loads or {}, ac)
        desired = max(ac["min_replicas"],
                      min(ac["max_replicas"],
                          math.ceil(total / ac["target_ongoing_requests"])
                          if total > 0 else ac["min_replicas"]))
        now = time.monotonic()
        with self._lock:
            dep = self.deployments.get(name)
            if dep is None:
                return
            cur = len(dep["replicas"])
            if desired == cur:
                dep["scale_pending_since"] = None
                return
            delay = ac["upscale_delay_s"] if desired > cur else \
                ac["downscale_delay_s"]
            since = dep["scale_pending_since"]
            if since is None:
                dep["scale_pending_since"] = now
                if delay > 0:
                    return
            elif now - since < delay:
                return
            dep["scale_pending_since"] = None
            if desired > cur:
                for _ in range(desired - cur):
                    try:
                        dep["replicas"].append(
                            self._start_replica(name, dep["config"]))
                    except Exception:  # noqa: BLE001
                        break
                self._bump_locked(name)
            else:
                # Prefer least-loaded victims; stop routing to them now
                # (removed from the table), then drain before killing so
                # in-flight requests finish (reference: graceful replica
                # shutdown in deployment_state reconciliation).
                ordered = sorted(
                    dep["replicas"],
                    key=lambda r: _load_value((loads or {}).get(r, 0.0)))
                victims = ordered[:cur - desired]
                dep["replicas"] = [r for r in dep["replicas"]
                                   if r not in victims]
                self._bump_locked(name)
        for r in victims if desired < cur else ():
            threading.Thread(target=self._drain_and_kill, args=(r,),
                             daemon=True).start()

    def _drain_and_kill(self, replica, timeout: float = 30.0) -> None:
        import ray_tpu

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                info = ray_tpu.get(replica.raytpu_probe.remote(),
                                   timeout=5)
                if info.get("pending", 0) == 0:
                    break
            except Exception:  # noqa: BLE001 - already dead
                break
            time.sleep(0.5)
        self._kill_replica(replica)

    def shutdown(self) -> bool:
        self._stop = True
        for name in list(self.deployments):
            self.delete_deployment(name)
        return True
