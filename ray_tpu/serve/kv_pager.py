"""Host-side block manager for the paged KV cache (the data plane
under serve/llm.py's continuous scheduler).

The jitted decode programs see only a preallocated block pool and
per-row block tables (decode_common paged contract); everything that
DECIDES which block holds what lives here, on the host:

  * **free-list allocation** — blocks 1..num_blocks-1 start free
    (block 0 is the reserved null block: never allocated, absorbs the
    masked pad scatter-writes the jitted programs route to it);
  * **refcounts** — a block referenced by several live sequences is
    shared; the last release returns it;
  * **prefix cache** — full prompt-token blocks are content-indexed
    (exact token-tuple keys, no hash collisions → no silent wrong
    reuse), so a request whose prompt extends a resident prefix skips
    re-prefilling those blocks entirely;
  * **cached LRU pool** — released-but-registered blocks stay resident
    (refcount 0) until allocation pressure evicts them
    least-recently-used, so popular prefixes survive across requests;
  * **copy-on-write** — before a sequence writes into a block it
    shares (the tail boundary of a prefix hit), `ensure_private`
    hands it a fresh block and tells the engine to device-copy the
    original (decode_common.copy_block).

Nothing here touches device memory — the pager returns block ids and
the engine stitches them into jitted calls.  Analogous data/control
split to vLLM's PagedAttention block manager, rebuilt TPU-side: the
pool is a static-shape jit argument, never reallocated.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.serve.kv_tier import HostKVTier
from ray_tpu.serve.kvscope import KVScope

__all__ = ["BlockPager"]

#: journal events tag evicted/re-registered keys by their first few
#: tokens (enough to eyeball which prefix churned) plus the full
#: length — full keys would bloat the bounded flightrec ring
_KEY_PREFIX_TOKENS = 8


class BlockPager:
    """Allocator + prefix index over a pool of `num_blocks` KV blocks
    of `block_size` token slots each.

    Block ids are ints in [1, num_blocks); 0 is the reserved null
    block.  Every returned block carries a refcount the caller must
    eventually `release`.  `num_blocks` must cover at least one full
    sequence (max_seq // block_size) or admission could never succeed.
    """

    def __init__(self, num_blocks: int, block_size: int, max_seq: int,
                 *, bytes_per_block: int = 0, tensor_shards: int = 1,
                 recorder=None,
                 host_tier: Optional[HostKVTier] = None):
        if max_seq % block_size:
            raise ValueError(f"max_seq={max_seq} must be a multiple of "
                             f"block_size={block_size}")
        if num_blocks < 1 + max_seq // block_size:
            raise ValueError(
                f"num_blocks={num_blocks} cannot hold one full "
                f"sequence ({max_seq // block_size} blocks + null)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_seq = int(max_seq)
        # accounting only — the pager never touches device memory.
        # bytes_per_block is the GLOBAL K+V footprint of one block
        # across all layers; tensor_shards is how many ways the pool's
        # head dim is split over the mesh, so stats() can report the
        # per-chip resident bytes a sharded pool actually costs.
        self.bytes_per_block = int(bytes_per_block)
        self.tensor_shards = max(1, int(tensor_shards))
        # LIFO free list: recently-freed blocks are re-used first
        # (warmer HBM pages on real hardware, denser tests)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        #: exact prompt-token prefix -> resident block id.  Keys are
        #: token tuples (content-addressed), so a block evicted and
        #: re-filled with other tokens can never falsely match.
        self._index: Dict[Tuple[int, ...], int] = {}
        self._block_key: Dict[int, Tuple[int, ...]] = {}
        #: refcount-0 registered blocks, insertion order == LRU order
        self._cached: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self.prefix_hits = 0      # blocks served from the cache
        self.prefix_misses = 0    # blocks that had to be prefilled
        self.cow_copies = 0
        self.evictions = 0
        #: chunked streaming prefill (round 15): fill events the
        #: engine reports as it writes reserved blocks chunk by chunk.
        #: partial_fills counts intermediate chunks (row parked after),
        #: fill_tokens the prompt tokens ingested through fills.
        self.partial_fills = 0
        self.fill_tokens = 0
        #: total keys handed out by prefix_keys() — how much affinity
        #: metadata this pager has published to routers
        self.prefix_keys_exported = 0
        #: optional flight recorder (_private/flightrec.py): block
        #: reserve / evict / free / COW decisions journal themselves
        #: so a postmortem can replay pool pressure around an anomaly
        self._recorder = recorder
        #: (request_id, trace_id, tenant) the engine sets around one
        #: admission's reservation window, so the kv_* journal events
        #: carry the request/trace/tenant a postmortem filters by and
        #: kvscope can attribute blocks + re-prefill waste per tenant
        self._req_ctx: Tuple[Optional[int], Optional[str],
                             Optional[str]] = (None, None, None)
        #: kvscope (serve/kvscope.py): occupancy ring + eviction
        #: forensics + re-prefill waste ledger over this pool
        self.scope = KVScope(self.num_blocks, self.block_size)
        #: tiered host-RAM KV cache (serve/kv_tier.py): evicted
        #: registered blocks spill device→host instead of vanishing,
        #: and `tier_lookup` gives HBM prefix misses a second chance.
        #: The pager still never touches device memory — the engine
        #: registers a block-saver callback (`set_block_saver`) that
        #: gathers a block's K/V rows to host at spill time.
        self.tier = host_tier
        self._block_saver: Optional[Callable[[int], Tuple]] = None

    def set_block_saver(self, fn: Callable[[int], Tuple]) -> None:
        """Register the engine's D2H gather: ``fn(block_id) ->
        (k_rows, v_rows)`` host arrays for one block across all
        layers.  Required before eviction can spill into the host
        tier; without it (or without a tier) eviction keeps its
        original discard semantics."""
        self._block_saver = fn

    def set_request(self, request_id: Optional[int],
                    trace_id: Optional[str] = None,
                    tenant: Optional[str] = None) -> None:
        """Scope subsequent recorder events to one request — the
        engine brackets each admission's pager calls with
        ``set_request(rec_id, trace_id, tenant)`` / ``set_request(None)``.
        Purely journal/attribution tagging; allocation behavior is
        unchanged."""
        self._req_ctx = (request_id, trace_id, tenant)

    def _ctx_tag(self) -> Dict[str, object]:
        req, trace, tenant = self._req_ctx
        if req is None:
            return {}
        tag: Dict[str, object] = {"req": req}
        if trace is not None:
            tag["trace"] = trace
        if tenant:
            tag["tenant"] = tenant
        return tag

    def _key_tag(self, key: Optional[Tuple[int, ...]]
                 ) -> Dict[str, object]:
        if key is None:
            return {}
        return {"key_prefix": list(key[:_KEY_PREFIX_TOKENS]),
                "key_len": len(key)}

    # -- capacity ------------------------------------------------------

    @property
    def blocks_free(self) -> int:
        """Immediately allocatable blocks (untouched free list)."""
        return len(self._free)

    @property
    def blocks_cached(self) -> int:
        """Refcount-0 registered blocks — evictable on demand."""
        return len(self._cached)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - 1 - len(self._free) - len(self._cached)

    @property
    def available(self) -> int:
        """Blocks an `allocate` call could produce right now."""
        return len(self._free) + len(self._cached)

    def blocks_needed(self, prompt_len: int, max_new_tokens: int,
                      headroom: int = 0) -> int:
        """Blocks a request needs end-to-end.  `headroom` reserves
        extra write positions past the generation budget — spec-decode
        verify rounds scatter up to k draft K/V writes beyond the last
        kept token, and those overshoot writes must land in blocks the
        row OWNS (never a shared prefix block or a block the pager has
        re-handed out).  Capped at max_seq: writes past the sequence
        bound are null-routed on-device and need no backing block."""
        want = min(prompt_len + max_new_tokens + headroom, self.max_seq)
        return -(-want // self.block_size)

    # -- allocation ----------------------------------------------------

    def allocate(self, count: int) -> Optional[List[int]]:
        """`count` private blocks (refcount 1 each), evicting cached
        prefix blocks LRU-first when the free list runs dry.  Returns
        None (allocating nothing) when even eviction cannot cover the
        request — the caller requeues and retries after a retirement.
        """
        if count > self.available:
            if self._recorder is not None and count:
                self._recorder.record("kv_exhausted", need=count,
                                      available=self.available,
                                      **self._ctx_tag())
            return None
        out: List[int] = []
        evicted = 0
        for _ in range(count):
            if not self._free:
                blk, _ = self._cached.popitem(last=False)  # LRU
                # forensics: capture the content key BEFORE the index
                # drops it — the kv_evict journal event and the
                # kvscope re-prefill ledger both need to know WHAT
                # was lost, not just that a block was reclaimed
                key = self._block_key.get(blk)
                owner = self.scope.note_evict(key)
                # tiered host-RAM KV cache: before the block id is
                # recycled, spill its K/V rows device→host so a later
                # admission can restore the prefix via H2D copy
                # instead of re-prefilling it (serve/kv_tier.py)
                spilled = 0
                if self.tier is not None and key is not None \
                        and self._block_saver is not None:
                    # resident key → the gather would copy identical
                    # bytes (content addressing); LRU-touch instead
                    spilled = self.tier.refresh(key)
                    if not spilled:
                        k_rows, v_rows = self._block_saver(blk)
                        spilled = self.tier.put(key, k_rows, v_rows)
                self._deregister(blk)
                self.evictions += 1
                evicted += 1
                self._free.append(blk)
                if self._recorder is not None:
                    # "tenant" names the VICTIM's owner (what was
                    # lost); req/trace still identify the evicting
                    # admission via the request context
                    tag = dict(self._ctx_tag(), **self._key_tag(key))
                    if owner:
                        tag["tenant"] = owner
                    if spilled:
                        tag["tier_bytes"] = spilled
                    self._recorder.record("kv_evict", block=blk,
                                          **tag)
            blk = self._free.pop()
            self._ref[blk] = 1
            out.append(blk)
        self.scope.note_alloc(out, self._req_ctx[2])
        if self._recorder is not None and count:
            self._recorder.record("kv_reserve", blocks=count,
                                  evicted=evicted,
                                  free=len(self._free),
                                  **self._ctx_tag())
        return out

    def release(self, block_ids: Sequence[int]) -> None:
        """Drop one reference on each block.  Zero-ref registered
        blocks park in the cached pool (prefix stays warm); zero-ref
        unregistered blocks return to the free list."""
        freed = 0
        for blk in block_ids:
            ref = self._ref.get(blk, 0) - 1
            if ref > 0:
                self._ref[blk] = ref
                continue
            if ref < 0:
                raise ValueError(f"release of unallocated block {blk}")
            del self._ref[blk]
            self.scope.note_block_released(blk)
            if blk in self._block_key:
                self._cached[blk] = None       # most-recently used
                self._cached.move_to_end(blk)
            else:
                self._free.append(blk)
            freed += 1
        if self._recorder is not None and freed:
            self._recorder.record("kv_free", blocks=freed,
                                  free=len(self._free),
                                  cached=len(self._cached),
                                  **self._ctx_tag())

    def note_fill(self, tokens: int, partial: bool = False) -> None:
        """Journal one prefill chunk writing `tokens` token slots into
        this pager's reserved blocks (chunked streaming prefill —
        serve/llm.py calls this per chunk).  `partial=True` marks an
        intermediate chunk: the row still has unfilled tail blocks and
        is parked until its next chunk window.  Pure accounting — the
        blocks were allocated at admission and ownership is unchanged;
        the counters surface in stats() and the `kv_fill` journal
        event lets a postmortem replay how a long prompt's blocks
        filled between decode waves."""
        self.fill_tokens += int(tokens)
        if partial:
            self.partial_fills += 1
        if self._recorder is not None:
            self._recorder.record("kv_fill", tokens=int(tokens),
                                  partial=bool(partial),
                                  **self._ctx_tag())

    # -- prefix cache --------------------------------------------------

    def match_prefix(self, tokens: Sequence[int]
                     ) -> Tuple[int, List[int]]:
        """Longest resident block-aligned prefix of `tokens`.

        Returns (prefix_len, matched_block_ids); each matched block's
        refcount is raised (cached blocks are revived), so the caller
        owns them and must `release` on retirement or admission
        failure.  prefix_len is capped at len(tokens) - 1: the tail
        prefill must ingest at least one token to produce the first
        logits — a full-prompt match reuses everything but the last
        position (whose recompute lands in a COW fork of the boundary
        block, see `ensure_private`)."""
        tokens = tuple(int(t) for t in tokens)
        n = len(tokens)
        matched: List[int] = []
        for i in range(1, n // self.block_size + 1):
            blk = self._index.get(tokens[:i * self.block_size])
            if blk is None:
                break
            matched.append(blk)
        prefix_len = min(len(matched) * self.block_size, max(n - 1, 0))
        for blk in matched:
            if blk in self._cached:            # revive from LRU pool
                del self._cached[blk]
                self._ref[blk] = 1
            else:
                self._ref[blk] += 1
        self.scope.note_alloc(matched, self._req_ctx[2])
        self.prefix_hits += len(matched)
        self.prefix_misses += self.blocks_needed(n, 0) - len(matched)
        return prefix_len, matched

    def tier_lookup(self, tokens: Sequence[int], matched: int
                    ) -> List[Tuple[Tuple[int, ...], Dict]]:
        """Second-chance prefix lookup against the host tier: walk
        the full-block keys of `tokens` past the first `matched` HBM
        blocks and collect consecutive tier entries, stopping at the
        first miss (same chain discipline as `match_prefix` — a gap
        cannot be skipped, the prefill must be contiguous).  The walk
        is capped where `match_prefix` caps: a reusable block must
        end at or before token ``len(tokens) - 1``, so the tail
        prefill still ingests at least one token.

        Returns ``[(key, entry), ...]`` — probes count into the
        tier's hit/miss stats; entries stay resident (the tier is a
        cache).  The caller allocates fresh blocks, H2D-installs each
        entry, then calls `note_tier_restore` to index them.  Empty
        when no tier is attached."""
        if self.tier is None:
            return []
        tokens = tuple(int(t) for t in tokens)
        n = len(tokens)
        out: List[Tuple[Tuple[int, ...], Dict]] = []
        for i in range(int(matched), max(n - 1, 0) // self.block_size):
            entry = self.tier.take(tokens[:(i + 1) * self.block_size])
            if entry is None:
                break
            out.append((tokens[:(i + 1) * self.block_size], entry))
        return out

    def note_tier_restore(self, pairs: Sequence[Tuple[Tuple[int, ...],
                                                      Dict]],
                          block_ids: Sequence[int]) -> int:
        """The engine H2D-installed `pairs` (from `tier_lookup`) into
        freshly-allocated `block_ids` — index them as resident prefix
        blocks.  Unlike `register_prefix`, this books NO re-prefill
        waste: the content came back via copy, not recompute — scope
        forensics record the saved work as ``tier_hits`` /
        ``tokens_restored`` instead, and each block journals a
        ``kv_fetch`` event naming key/tenant/bytes.  The restored
        blocks count as prefix HITS (served from cache, just a slower
        tier), so ``prefill_tokens`` — the waste-frac denominator —
        keeps meaning 'tokens actually prefilled'.  Returns the token
        slots restored."""
        tenant = self._req_ctx[2]
        restored = 0
        for (key, entry), blk in zip(pairs, block_ids):
            self._index[key] = blk
            self._block_key[blk] = key
            self.scope.note_tier_hit(key, tenant)
            restored += self.block_size
            if self._recorder is not None:
                self._recorder.record(
                    "kv_fetch", block=blk, tokens=self.block_size,
                    bytes=int(entry.get("bytes", 0)),
                    **dict(self._ctx_tag(), **self._key_tag(key)))
        nblocks = len(pairs)
        self.prefix_hits += nblocks
        self.prefix_misses -= nblocks
        if self.tier is not None:
            self.tier.note_restored(restored)
        return restored

    def register_prefix(self, tokens: Sequence[int],
                        block_ids: Sequence[int]) -> int:
        """Index every FULL prompt block of `tokens` (block i holds
        K/V for tokens[i*bs:(i+1)*bs]) so later prompts can match it.
        First writer wins: keys already indexed keep their canonical
        block (the duplicate block simply stays unregistered).

        Returns the re-prefill waste tokens kvscope booked — the sum
        over registered keys that were previously evicted (content
        the pool already held once and had to re-fill from scratch).
        """
        tokens = tuple(int(t) for t in tokens)
        tenant = self._req_ctx[2]
        waste = 0
        for i in range(len(tokens) // self.block_size):
            key = tokens[:(i + 1) * self.block_size]
            blk = block_ids[i]
            if key in self._index or blk in self._block_key:
                continue
            self._index[key] = blk
            self._block_key[blk] = key
            booked = self.scope.note_register(key, tenant)
            if booked:
                waste += booked
                if self._recorder is not None:
                    self._recorder.record(
                        "kv_reprefill", block=blk, tokens=booked,
                        **dict(self._ctx_tag(), **self._key_tag(key)))
        return waste

    def note_handoff_import(self, tokens: Sequence[int],
                            block_ids: Sequence[int]) -> None:
        """Index the FULL prompt blocks a disaggregated handoff just
        installed (serve/router.py two-stage dispatch): this decode
        replica received the rows by device or staged copy from a
        prefill replica, so unlike ``register_prefix`` nothing was
        recomputed and no probe happened — NO re-prefill waste is
        booked and the prefix hit/miss counters stay untouched.
        First writer wins, exactly like ``register_prefix``: keys
        already indexed keep their canonical block."""
        tokens = tuple(int(t) for t in tokens)
        tenant = self._req_ctx[2]
        indexed = 0
        for i in range(len(tokens) // self.block_size):
            key = tokens[:(i + 1) * self.block_size]
            blk = block_ids[i]
            if key in self._index or blk in self._block_key:
                continue
            self._index[key] = blk
            self._block_key[blk] = key
            self.scope.note_handoff_import(key, tenant)
            indexed += 1
        if self._recorder is not None and indexed:
            self._recorder.record(
                "kv_handoff_import", blocks=indexed,
                **self._ctx_tag())

    def ensure_private(self, block_id: int
                       ) -> Tuple[int, Optional[int]]:
        """Copy-on-write gate: called before a sequence writes into
        `block_id` (the prefix/tail boundary block of a prefix hit).

        A block is writable in place only when this sequence is its
        sole referent AND it is not indexed (an indexed block's
        content is a promise to future matchers).  Otherwise the
        caller's reference moves to a fresh block and (new_id, src_id)
        is returned — the caller must device-copy src → new before
        the write.  Returns (block_id, None) when no fork was needed;
        raises MemoryError when no block can be allocated (caller
        rolls back + requeues)."""
        shared = self._ref.get(block_id, 0) > 1 \
            or block_id in self._block_key
        if not shared:
            return block_id, None
        fresh = self.allocate(1)
        if fresh is None:
            raise MemoryError("no free block for copy-on-write fork")
        self.release([block_id])       # our ref moves to the fork
        self.cow_copies += 1
        if self._recorder is not None:
            # forensics: the forked block's content key (when it is a
            # registered prefix boundary) names WHICH prefix diverged
            self._recorder.record(
                "kv_cow", src=block_id, fork=fresh[0],
                **dict(self._ctx_tag(),
                       **self._key_tag(self._block_key.get(block_id))))
        return fresh[0], block_id

    def prefix_keys(self) -> List[Tuple[int, ...]]:
        """Resident prefix keys (exact block-aligned token tuples),
        exported as cluster-visible routing metadata.

        A fleet router (serve/router.py) matches an incoming prompt's
        block-aligned prefixes against each replica's exported keys and
        sends the request where the KV blocks already live.  The keys
        are content (token tuples), not block ids — a router on another
        host can match them without sharing this pager's id space.
        Every call bumps `prefix_keys_exported` (surfaced in stats()),
        so dashboards can see how much metadata the replica publishes.
        """
        keys = list(self._index.keys())
        self.prefix_keys_exported += len(keys)
        return keys

    def _deregister(self, block_id: int) -> None:
        key = self._block_key.pop(block_id, None)
        if key is not None:
            self._index.pop(key, None)

    # -- introspection -------------------------------------------------

    def sample_occupancy(self) -> None:
        """Append one kvscope occupancy snapshot — the engine calls
        this once per wave, so the ring replays pool pressure at
        scheduling granularity without journaling every allocation."""
        self.scope.sample(self._free, len(self._cached))

    def kv_scope_stats(self) -> Dict[str, object]:
        """The occupancy/forensics half of ``engine_stats()``'s
        ``kv_scope`` block.  ``prefill_tokens`` (the waste-fraction
        denominator) counts prefilled blocks in token units — the
        same block-granular unit the waste ledger books — so
        ``reprefill_waste_frac`` is exactly 'fraction of prefilled
        blocks that re-filled previously-resident content'.  The HBM
        ledger is composed by the deployment, which owns the device
        view."""
        return self.scope.stats(
            free=len(self._free), cached=len(self._cached),
            prefill_tokens=self.prefix_misses * self.block_size)

    def stats(self) -> Dict[str, float]:
        total = self.prefix_hits + self.prefix_misses
        out = {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_in_use": self.blocks_in_use,
            "blocks_cached": self.blocks_cached,
            "blocks_free": self.blocks_free,
            "prefix_block_hits": self.prefix_hits,
            "prefix_block_misses": self.prefix_misses,
            "prefix_hit_rate": round(self.prefix_hits / total, 4)
            if total else 0.0,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            "partial_fills": self.partial_fills,
            "fill_tokens": self.fill_tokens,
            "prefix_keys_resident": len(self._index),
            "prefix_keys_exported": self.prefix_keys_exported,
        }
        if self.bytes_per_block:
            out["pool_bytes"] = self.bytes_per_block * self.num_blocks
            out["pool_bytes_per_chip"] = \
                out["pool_bytes"] // self.tensor_shards
            out["tensor_shards"] = self.tensor_shards
        return out
