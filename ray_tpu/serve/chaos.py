"""Seeded chaos fault injection for the serve fleet.

Healthwatch (serve/health.py) is only trustworthy if its detection
paths are *exercised*, deterministically, in tests and benches — this
module is the fault generator.  A frozen :class:`ChaosConfig` names
the faults; :class:`ChaosInjector` is the runtime the fleet threads
through ``build_llm_fleet(chaos=)``:

* **freeze** — one replica's engine loop stops processing for
  ``freeze_waves`` wave windows after ``freeze_after_waves`` real
  waves: the loop polls ``asyncio.sleep(freeze_poll_ms)`` without
  heartbeating, exactly what a wedged host looks like to the monitor
  (heartbeats stop, admitted requests go token-silent, queued
  requests strand).  The freeze instant stamps
  ``HealthMonitor.note_fault`` so the DEAD transition carries
  ``time_to_detect_ms``.
* **token delay** — one replica's waves each stall an extra
  ``delay_token_ms`` for ``delay_token_waves`` waves: the loop still
  heartbeats but its requests go token-silent, the stall-detection
  path (heartbeat-death cannot catch this one).
* **handoff drop** — the Nth prefill→decode handoff package is
  dropped in the router (disaggregated fleets): the router journals
  ``handoff_dropped`` and recovers by re-running the request's prompt
  from scratch on a decode-capable replica, so the caller still gets
  a bit-identical (greedy) result.

Everything is inert unless armed: ``build_llm_fleet(chaos=None)``
(the default) attaches nothing to the engines — the hot path's only
cost is one ``is None`` check per wave — and a default
``ChaosConfig()`` arms no fault.  Replica targeting is by build-order
index (``bind`` order: prefill replicas first, then decode/both, the
fleet listing order) or by full replica name.

Clock discipline matches telemetry: monotonic ``perf_counter`` only
(graftcheck's ``wallclock-in-telemetry`` rule covers this file), and
the only sleeps are ``asyncio.sleep`` awaited by the engine loop.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Dict, List, Optional, Union

__all__ = ["ChaosConfig", "ChaosInjector"]


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """One fleet's fault plan.  ``freeze_replica`` /
    ``delay_token_replica`` select the victim by build-order index
    (int) or replica name (str); None disarms that fault.
    ``drop_handoff_nth`` drops the Nth handoff package (1-based; 0
    never drops).  ``seed`` keys any randomized choices so a chaos
    run replays exactly."""

    seed: int = 0
    freeze_replica: Optional[Union[int, str]] = None
    freeze_after_waves: int = 2
    freeze_waves: int = 20
    freeze_poll_ms: float = 5.0
    delay_token_replica: Optional[Union[int, str]] = None
    delay_token_ms: float = 0.0
    delay_token_waves: int = 0
    drop_handoff_nth: int = 0

    def __post_init__(self):
        if self.freeze_after_waves < 0 or self.freeze_waves < 0:
            raise ValueError(
                "freeze_after_waves/freeze_waves must be >= 0, got "
                f"{self.freeze_after_waves}/{self.freeze_waves}")
        if self.freeze_poll_ms <= 0:
            raise ValueError(
                f"freeze_poll_ms must be > 0, got "
                f"{self.freeze_poll_ms}")
        if self.delay_token_ms < 0 or self.delay_token_waves < 0:
            raise ValueError(
                "delay_token_ms/delay_token_waves must be >= 0, got "
                f"{self.delay_token_ms}/{self.delay_token_waves}")
        if self.drop_handoff_nth < 0:
            raise ValueError(
                f"drop_handoff_nth must be >= 0, got "
                f"{self.drop_handoff_nth}")

    def any_faults(self) -> bool:
        return ((self.freeze_replica is not None
                 and self.freeze_waves > 0)
                or (self.delay_token_replica is not None
                    and self.delay_token_ms > 0
                    and self.delay_token_waves > 0)
                or self.drop_handoff_nth > 0)


class ChaosInjector:
    """Runtime fault state shared by a fleet's replicas.  The engine
    loop asks :meth:`frozen` / :meth:`token_delay_s` per wave; the
    router asks :meth:`should_drop_handoff` per package.  Single
    event-loop discipline (same as the router) — no lock needed."""

    def __init__(self, config: ChaosConfig, monitor=None):
        self.config = config
        #: HealthMonitor (or None) — fault instants stamp note_fault
        #: so detection latency is measured from injection
        self._monitor = monitor
        self._rng = random.Random(config.seed)
        self._names: List[str] = []        # bind order = replica index
        self._waves: Dict[str, int] = {}   # real (unfrozen) waves run
        self._frozen_polls: Dict[str, int] = {}
        self._delayed_waves: Dict[str, int] = {}
        self._fault_noted: set = set()
        self._handoffs_seen = 0
        self.dropped_handoffs = 0
        self.freeze_poll_s = config.freeze_poll_ms / 1e3

    def bind(self, replica: str) -> None:
        """Register one replica in fleet build order — the order an
        int ``freeze_replica`` / ``delay_token_replica`` indexes."""
        if replica not in self._names:
            self._names.append(replica)

    def _matches(self, which: Optional[Union[int, str]],
                 replica: str) -> bool:
        if which is None:
            return False
        if isinstance(which, int):
            return (0 <= which < len(self._names)
                    and self._names[which] == replica)
        return replica == which

    def _note_fault(self, replica: str, kind: str) -> None:
        key = (replica, kind)
        if key in self._fault_noted:
            return
        self._fault_noted.add(key)
        if self._monitor is not None:
            self._monitor.note_fault(replica, kind=kind)

    # -- engine-loop hooks (serve/llm.py _engine) ----------------------

    def frozen(self, replica: str) -> bool:
        """Is this wave frozen for `replica`?  True for
        ``freeze_waves`` consecutive poll windows once the replica has
        run ``freeze_after_waves`` real waves; the engine loop then
        awaits ``freeze_poll_s`` and re-asks instead of processing
        (and, crucially, instead of heartbeating)."""
        cfg = self.config
        if cfg.freeze_waves > 0 \
                and self._matches(cfg.freeze_replica, replica) \
                and self._waves.get(replica, 0) \
                >= cfg.freeze_after_waves:
            polls = self._frozen_polls.get(replica, 0)
            if polls < cfg.freeze_waves:
                self._frozen_polls[replica] = polls + 1
                self._note_fault(replica, "freeze")
                return True
        self._waves[replica] = self._waves.get(replica, 0) + 1
        return False

    def token_delay_s(self, replica: str) -> float:
        """Extra per-wave stall for the delay victim (0.0 otherwise):
        tokens still flow, just ``delay_token_ms`` late — the
        token-silence shape only the stall sweep can detect."""
        cfg = self.config
        if cfg.delay_token_ms <= 0 \
                or not self._matches(cfg.delay_token_replica, replica):
            return 0.0
        done = self._delayed_waves.get(replica, 0)
        if done >= cfg.delay_token_waves:
            return 0.0
        self._delayed_waves[replica] = done + 1
        self._note_fault(replica, "token_delay")
        return cfg.delay_token_ms / 1e3

    # -- router hook (serve/router.py _forward_handoff) ----------------

    def should_drop_handoff(self) -> bool:
        """Drop the Nth handoff package (1-based counter over every
        package the router forwards)."""
        if self.config.drop_handoff_nth <= 0:
            return False
        self._handoffs_seen += 1
        if self._handoffs_seen == self.config.drop_handoff_nth:
            self.dropped_handoffs += 1
            return True
        return False

    def stats(self) -> Dict[str, Any]:
        return {
            "armed": self.config.any_faults(),
            "seed": self.config.seed,
            "replicas": list(self._names),
            "frozen_polls": dict(self._frozen_polls),
            "delayed_waves": dict(self._delayed_waves),
            "handoffs_seen": self._handoffs_seen,
            "dropped_handoffs": self.dropped_handoffs,
        }
