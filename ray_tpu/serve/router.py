"""Fleet control plane: prefix-aware routing, per-tenant weighted
fair queueing, and SLO-driven autoscaling over N continuous engines.

One continuous-batching engine (serve/llm.py) cannot serve heavy
traffic alone; this module composes N of them into a horizontally
scalable fleet behind one router, in the shape of Ray Serve's
controller/router split (reference: serve controller.py ServeController
+ router.py assign_request) with the Ray paper's resource-demand
scaling as the autoscaling model:

* **Prefix-affinity routing** — every replica's BlockPager publishes
  its resident prefix keys (`prefix_keys()`, exact block-aligned token
  tuples) as cluster-visible metadata.  The router matches an incoming
  prompt's block prefixes against each replica's export and sends the
  request where the KV blocks already live, so shared-prefix traffic
  concentrates its cache instead of re-prefilling the same system
  prompt on every replica.  On a miss it falls back to
  least-outstanding-requests over two random candidates
  (power-of-two-choices), the classic load-balancing compromise
  between random (no state) and global-least-loaded (herd risk).

* **Weighted fair queueing** — requests carry a tenant; each tenant
  class has a weight, and a virtual-time WFQ (start-time fair
  queueing: tag = max(V, tenant_last_finish) + cost/weight, serve
  min-tag first) decides which queued request dispatches when replica
  capacity frees.  A saturating batch tenant therefore cannot starve
  an interactive tenant's TTFT: the interactive class's small virtual
  cost lets its requests overtake the batch backlog.

* **SLO-driven autoscaling** — `LLMFleet.autoscale_step` reads
  burn-rate (serve/slo.py, 30s window) and queue-depth signals through
  the same pluggable signal seam as ServeController (LOAD_SIGNALS in
  serve/controller.py), scales up on a sustained breach, scales down
  on sustained idle, respects cooldowns and min/max bounds, and
  retires replicas with a graceful drain: stop admitting, finish
  in-flight requests, verify every KV block is freed, then shut the
  engine down.  Every decision journals to the fleet flight recorder
  (`route` / `scale_up` / `scale_down` / `drain` events via
  serve/telemetry.py), so `python -m ray_tpu.tools.flightrec report`
  can reconstruct the routing table post-hoc.

Everything here is host-side control logic — replicas are in-process
engine instances sharing one jit cache (equal configs compile once),
and the router never touches device memory.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import heapq
import itertools
import random
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private import telemetry as _core
from ray_tpu.serve.batching import HandoffCursor
from ray_tpu.serve.chaos import ChaosConfig, ChaosInjector
from ray_tpu.serve.health import (DEAD, HEALTHY, HealthConfig,
                                  HealthMonitor, empty_fleet_health,
                                  healthwatch_enabled)
from ray_tpu.serve.slo import worst_burn_rate
from ray_tpu.serve.telemetry import (EngineTelemetry, TraceContext,
                                     _tracebus_enabled, latency_anatomy,
                                     merge_anatomy_samples)

__all__ = ["TenantClass", "DEFAULT_TENANT", "FairQueue",
           "AutoscalePolicy", "LLMRouter", "LLMFleet",
           "build_llm_fleet", "fleet_registry"]


@dataclasses.dataclass(frozen=True)
class TenantClass:
    """One traffic class: a WFQ weight plus optional latency targets.

    `weight` is the tenant's fair share of router dispatch slots —
    an interactive class with weight 8 overtakes a batch class with
    weight 1 whenever both have queued requests.  `ttft_ms` / `e2e_ms`
    are the per-tenant SLO targets the fleet's `tenant_report()`
    scores attainment against (None = objective not tracked);
    `objective` is the attainment the tenant is promised."""

    name: str
    weight: float = 1.0
    ttft_ms: Optional[float] = None
    e2e_ms: Optional[float] = None
    objective: float = 0.95

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be "
                             f"> 0, got {self.weight}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"tenant {self.name!r}: objective must "
                             f"be in (0, 1), got {self.objective}")

    def objectives(self) -> Dict[str, float]:
        out = {}
        if self.ttft_ms is not None:
            out["ttft"] = float(self.ttft_ms)
        if self.e2e_ms is not None:
            out["e2e"] = float(self.e2e_ms)
        return out


DEFAULT_TENANT = TenantClass("default", weight=1.0)


class FairQueue:
    """Virtual-time weighted fair queue (start-time fair queueing).

    Each pushed item gets a finish tag ``start + cost/weight`` where
    ``start = max(V, tenant's last finish)``; pop serves the minimum
    finish tag and advances V to the served item's start tag.  With
    unit cost per request, a tenant with weight w receives a w-
    proportional share of pops whenever it is backlogged, and an idle
    tenant's unused share redistributes automatically — no token
    buckets, no timers, fully deterministic given arrival order."""

    def __init__(self, tenants: Optional[Dict[str, TenantClass]] = None):
        self._tenants = dict(tenants or {})
        self._vtime = 0.0
        self._last_finish: Dict[str, float] = {}
        self._heap: List[Tuple[float, int, float, Any]] = []
        self._seq = itertools.count()

    def __len__(self) -> int:
        return len(self._heap)

    def _class_of(self, tenant: Optional[str]) -> TenantClass:
        if tenant is None:
            return DEFAULT_TENANT
        return self._tenants.get(tenant,
                                 TenantClass(tenant, weight=1.0))

    def push(self, item: Any, tenant: Optional[str] = None,
             cost: float = 1.0) -> None:
        tc = self._class_of(tenant)
        start = max(self._vtime,
                    self._last_finish.get(tc.name, 0.0))
        finish = start + float(cost) / tc.weight
        self._last_finish[tc.name] = finish
        heapq.heappush(self._heap,
                       (finish, next(self._seq), start, item))

    def pop(self) -> Any:
        finish, _seq, start, item = heapq.heappop(self._heap)
        self._vtime = max(self._vtime, start)
        return item


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Knobs for `LLMFleet.autoscale_step` (see docs/serve.md).

    Scale UP when the worst replica burn rate exceeds `burn_threshold`
    or router backlog per live replica exceeds `queue_high`, sustained
    for `sustain_s`; scale DOWN when the fleet is completely idle (no
    queue, no in-flight, no burn) for `idle_s`.  `up_cooldown_s` /
    `down_cooldown_s` are minimum gaps between same-direction actions
    so one breach cannot thrash the fleet."""

    min_replicas: int = 1
    max_replicas: int = 8
    burn_threshold: float = 1.0
    queue_high: float = 4.0
    sustain_s: float = 5.0
    idle_s: float = 30.0
    up_cooldown_s: float = 10.0
    down_cooldown_s: float = 30.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")


class ReplicaHandle:
    """Router-side view of one engine replica: identity, role,
    outstanding count, drain flag, and the latest prefix-key
    export."""

    def __init__(self, name: str, inst: Any):
        self.name = name
        self.inst = inst
        #: "both" (monolithic), "prefill", or "decode" — read off the
        #: engine so the router's two-stage scheduler and the fleet's
        #: role-aware pooling never guess from names
        self.role = str(getattr(inst, "role", "both"))
        self.inflight = 0
        self.routed = 0
        self.draining = False
        self._keys: frozenset = frozenset()

    def free_blocks(self) -> int:
        """Blocks this replica's pager could allocate right now — the
        handoff target score (a decode replica must hold the whole
        chain, so free-block headroom beats raw request count)."""
        pager = getattr(self.inst, "_pager", None)
        return int(pager.available) if pager is not None else 0

    def refresh_metadata(self) -> None:
        """Pull the replica's resident prefix keys (the BlockPager
        export) into the router's view.  In-process this is a dict-key
        copy; a cross-host router would receive the same token tuples
        over the metadata channel."""
        pager = getattr(self.inst, "_pager", None)
        self._keys = (frozenset(pager.prefix_keys())
                      if pager is not None else frozenset())

    def prefix_match(self, tokens: Tuple[int, ...],
                     block_size: int) -> int:
        """Longest run of this replica's resident blocks covering a
        prefix of `tokens`, in blocks."""
        n = 0
        for i in range(1, len(tokens) // block_size + 1):
            if tokens[:i * block_size] in self._keys:
                n = i
            else:
                break
        return n

    def engine_stats(self) -> Dict[str, Any]:
        return self.inst.engine_stats()


class LLMRouter:
    """Routes requests over a mutable set of replicas.

    `policy` is "prefix" (affinity by resident prefix keys, p2c
    fallback) or "round_robin" (the baseline the fleet tests compare
    against).  With `wfq=True` queued requests dispatch in weighted-
    fair order per tenant; otherwise strict FIFO.  At most
    `max_inflight_per_replica` requests are outstanding per replica —
    the backlog stays HERE, where WFQ can reorder it, instead of in
    the engines' FIFO queues where it could not."""

    def __init__(self, replicas: List[ReplicaHandle], *,
                 block_size: int = 16,
                 tenants: Optional[Sequence[TenantClass]] = None,
                 policy: str = "prefix", wfq: bool = True,
                 max_inflight_per_replica: Optional[int] = None,
                 seed: int = 0,
                 telemetry: Optional[EngineTelemetry] = None,
                 name: str = "llm_fleet",
                 health: Optional[HealthMonitor] = None,
                 chaos: Optional[ChaosInjector] = None):
        if policy not in ("prefix", "round_robin"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self._replicas = replicas          # shared with LLMFleet
        #: fleet HealthMonitor (None = healthwatch off) — consulted at
        #: every pump so DEAD replicas are skipped and SUSPECT ones
        #: deprioritized without any extra control loop
        self._health = health
        self._chaos = chaos
        #: not-yet-admitted requests rescued off DEAD replicas' engine
        #: queues and push_front-requeued to healthy peers
        self.requeued_on_death = 0
        self._block_size = int(block_size)
        self.tenants: Dict[str, TenantClass] = {
            t.name: t for t in (tenants or ())}
        self.policy = policy
        self._wfq = FairQueue(self.tenants) if wfq else None
        self._fifo: collections.deque = collections.deque()
        self._cap = max_inflight_per_replica
        self._rng = random.Random(seed)
        self._rr = 0
        self._ids = itertools.count()
        self.telemetry = telemetry or EngineTelemetry(name)
        self.routed_by_policy = {"prefix_affinity": 0, "p2c": 0,
                                 "round_robin": 0, "disagg_prefill": 0}
        #: completed second-stage moves (prefill → decode replica)
        self.handoffs = 0

    # -- introspection -------------------------------------------------

    @property
    def live_replicas(self) -> List[ReplicaHandle]:
        return [r for r in self._replicas if not r.draining]

    def queue_depth(self) -> int:
        return len(self._wfq) if self._wfq is not None \
            else len(self._fifo)

    def total_inflight(self) -> int:
        return sum(r.inflight for r in self._replicas)

    # -- submission ----------------------------------------------------

    def _normalize(self, prompt) -> np.ndarray:
        return np.asarray(prompt, np.int32).reshape(-1)

    async def submit(self, prompt, tenant: Optional[str] = None,
                     sampling=None):
        """Route one request and await its completion.  `tenant`
        selects the WFQ class and tags the engine-side record for
        per-tenant SLO slicing; the submit instant is threaded to the
        engine as the request's enqueue time so TTFT/e2e include any
        router queueing."""
        if not self.live_replicas:
            raise RuntimeError("no live replicas to route to")
        arr = self._normalize(prompt)
        t_submit = time.perf_counter()
        # the request's causal identity for the tracebus, born HERE —
        # threaded to the engine alongside enqueue_ts so router wait,
        # engine queue wait, and device work stitch on one clock
        ctx = (TraceContext(origin="router")
               if _tracebus_enabled() else None)
        fut = asyncio.get_running_loop().create_future()
        item = (arr, tenant, sampling, t_submit, fut,
                next(self._ids), ctx)
        if self._wfq is not None:
            self._wfq.push(item, tenant)
        else:
            self._fifo.append(item)
        self._pump()
        return await fut

    # -- dispatch ------------------------------------------------------

    def _state_of(self, rep: ReplicaHandle) -> str:
        return (self._health.state(rep.name)
                if self._health is not None else HEALTHY)

    def _prefer_healthy(self, cands: List[ReplicaHandle]
                        ) -> List[ReplicaHandle]:
        """SUSPECT deprioritization: route to HEALTHY replicas while
        any exist; a fleet that is ALL suspect still serves (suspicion
        is a hint, not a verdict — only DEAD is disqualifying)."""
        if self._health is None:
            return cands
        healthy = [r for r in cands
                   if self._state_of(r) == HEALTHY]
        return healthy or cands

    def _candidates(self, reps: Optional[List[ReplicaHandle]] = None
                    ) -> List[ReplicaHandle]:
        live = self.live_replicas if reps is None \
            else [r for r in reps if not r.draining]
        if self._health is not None:
            live = [r for r in live if self._state_of(r) != DEAD]
        if self._cap is None:
            return live
        return [r for r in live if r.inflight < self._cap]

    @property
    def disaggregated(self) -> bool:
        return any(r.role == "prefill" for r in self.live_replicas)

    def _pick_disagg(self, tokens: Tuple[int, ...],
                     pre: List[ReplicaHandle],
                     dec: List[ReplicaHandle]
                     ) -> Tuple[ReplicaHandle, str, int]:
        """Stage one of disaggregated routing.  Prefix affinity still
        wins, and it wins BIGGER here: a decode replica already
        holding the prompt's prefix blocks serves the request whole —
        its paged prefill of the unmatched tail is exactly the work a
        handoff would have shipped over, so the prefill fleet is
        skipped entirely.  Otherwise the request admits to the
        least-loaded prefill replica and rides the handoff path."""
        if self.policy == "prefix":
            best, best_match = None, 0
            for rep in self._prefer_healthy(dec):
                rep.refresh_metadata()
                m = rep.prefix_match(tokens, self._block_size)
                if m > best_match:
                    best, best_match = rep, m
            if best is not None:
                return best, "prefix_affinity", best_match
        rep = min(self._prefer_healthy(pre),
                  key=lambda r: r.inflight)
        return rep, "disagg_prefill", 0

    def _pick(self, tokens: Tuple[int, ...],
              cands: List[ReplicaHandle]
              ) -> Tuple[ReplicaHandle, str, int]:
        cands = self._prefer_healthy(cands)
        if self.policy == "round_robin":
            rep = cands[self._rr % len(cands)]
            self._rr += 1
            return rep, "round_robin", 0
        best, best_match = None, 0
        for rep in cands:
            rep.refresh_metadata()
            m = rep.prefix_match(tokens, self._block_size)
            if m > best_match:
                best, best_match = rep, m
        if best is not None:
            return best, "prefix_affinity", best_match
        if len(cands) == 1:
            return cands[0], "p2c", 0
        a, b = self._rng.sample(cands, 2)
        rep = a if a.inflight <= b.inflight else b
        return rep, "p2c", 0

    def _health_sweep(self) -> None:
        """Liveness consult at every pump: age heartbeats (throttled
        by the monitor's probe interval) and rescue the engine-queued
        requests of any replica the sweep finds DEAD.  Idempotent —
        a dead replica with an empty queue costs one state read."""
        if self._health is None:
            return
        self._health.maybe_probe()
        for rep in self._replicas:
            if not rep.draining and self._state_of(rep) == DEAD:
                self._requeue_dead(rep)

    def _requeue_dead(self, dead: ReplicaHandle) -> int:
        """Rescue the DEAD replica's not-yet-admitted engine queue:
        every queued prompt is push_front-requeued to a healthy
        compatible replica with its ORIGINAL future and a fresh
        engine-side record backdated to the original enqueue instant,
        so the caller still gets its result and TTFT/e2e still charge
        the full wait.  Requests already admitted to slots are the
        dead engine's to finish (or fail) — recovery proper is ROADMAP
        item 4; this is the detection + queue-rescue substrate.
        Handoff packages stay queued on the dead replica (their KV
        block rows live in ITS pager — nothing to rescue host-side)."""
        q = getattr(dead.inst, "_queue", None)
        if q is None or not len(q):
            return 0
        # role compatibility: "both" replicas take anything; a dead
        # "both" replica's prompts may also land on "decode" peers
        # (decode engines paged-prefill whole requests — the same
        # bypass _pick_disagg's prefix-affinity path uses)
        ok_roles = {"both", dead.role}
        if dead.role == "both":
            ok_roles.add("decode")
        targets = [r for r in self._replicas
                   if not r.draining and r is not dead
                   and r.role in ok_roles
                   and self._state_of(r) == HEALTHY
                   and getattr(r.inst, "_wake", None) is not None]
        items = q.pop(len(q))
        if not targets:
            for (arg, rec, sp), fut in reversed(items):
                q.push_front((arg, rec, sp), fut)
            return 0
        moved = 0
        stay = []
        for (arg, rec, sp), fut in items:
            if isinstance(arg, HandoffCursor):
                stay.append(((arg, rec, sp), fut))
                continue
            dead.inst._telemetry.record_requeue(
                rec, reason="replica_dead")
            target = min(targets, key=lambda r: (
                len(r.inst._queue), r.inflight))
            rec2 = target.inst._telemetry.record_enqueue(
                int(arg.shape[0]), now=rec.get("enqueue"),
                tenant=rec.get("tenant"), ctx=rec.get("ctx"))
            target.inst._queue.push_front((arg, rec2, sp), fut)
            target.inst._wake.set()
            moved += 1
        for (arg, rec, sp), fut in reversed(stay):
            q.push_front((arg, rec, sp), fut)
        if moved:
            self.requeued_on_death += moved
            self._health.note_requeued(moved)
        return moved

    def _pump(self) -> None:
        """Dispatch queued requests while replica capacity is free.
        Synchronous and re-entrant-safe: called on submit, on every
        completion, and when the replica set changes."""
        self._health_sweep()
        while self.queue_depth() > 0:
            live = self.live_replicas
            pre = [r for r in live if r.role == "prefill"]
            if pre:
                # two-stage disaggregated dispatch gates on prefill
                # capacity (the handoff target is chosen later, when
                # the package exists and free-block counts are fresh)
                cands = self._candidates(pre)
                dec = [r for r in live
                       if r.role in ("decode", "both")]
            else:
                cands = self._candidates()
                dec = []
            if not cands:
                return
            if self._wfq is not None:
                item = self._wfq.pop()
            else:
                item = self._fifo.popleft()
            arr, tenant, sampling, t_submit, fut, rid, ctx = item
            tokens = tuple(int(t) for t in arr)
            if pre:
                rep, policy, matched = self._pick_disagg(
                    tokens, cands, dec)
            else:
                rep, policy, matched = self._pick(tokens, cands)
            self.routed_by_policy[policy] += 1
            if ctx is not None:
                # the router hop: submit → dispatch, with the routing
                # decision as span attributes
                ctx.span("router.route", t_submit,
                         time.perf_counter(), replica=rep.name,
                         policy=policy, tenant=tenant,
                         matched_blocks=matched, router_req=rid)
            self.telemetry.record_route(
                req=rid, replica=rep.name, policy=policy,
                tenant=tenant, matched_blocks=matched,
                outstanding=rep.inflight,
                **({"trace": ctx.trace_id} if ctx is not None else {}))
            rep.inflight += 1
            rep.routed += 1
            asyncio.get_running_loop().create_task(
                self._dispatch(rep, arr, tenant, sampling, t_submit,
                               fut, ctx, rid))

    def _pick_handoff_target(self) -> ReplicaHandle:
        """Stage two: the decode replica to install a handoff package
        on — most free pager blocks first (the install must hold the
        request's WHOLE chain), outstanding slots break ties.  A
        package may exceed the inflight cap: the request already won
        its admission at stage one, and the decode engine's own
        queue/requeue machinery absorbs any wait."""
        dec = [r for r in self.live_replicas
               if r.role in ("decode", "both")
               and self._state_of(r) != DEAD]
        if not dec:
            raise RuntimeError(
                "no live decode replicas to hand off to")
        dec = self._prefer_healthy(dec)
        under = [r for r in dec
                 if self._cap is None or r.inflight < self._cap]
        pool = under or dec
        return max(pool, key=lambda r: (r.free_blocks(), -r.inflight))

    async def _forward_handoff(self, pkg, tenant, ctx, rid: int):
        if self._chaos is not None \
                and self._chaos.should_drop_handoff():
            # chaos: the package "got lost on the wire".  Journal the
            # drop and recover by re-running the prompt from scratch
            # on a decode-capable replica (decode engines paged-
            # prefill whole requests) — greedy decoding makes the
            # recovered result bit-identical, only slower.
            self.telemetry.flightrec.record(
                "handoff_dropped", req=rid,
                n_blocks=int(pkg.n_blocks),
                **({"trace": ctx.trace_id} if ctx is not None else {}))
            meta = pkg.meta or {}
            rep = self._pick_handoff_target()
            rep.inflight += 1
            rep.routed += 1
            try:
                return await rep.inst(
                    pkg.prompt, sampling=pkg.sampling, tenant=tenant,
                    enqueue_ts=meta.get("enqueue"), trace=ctx)
            finally:
                rep.inflight -= 1
                self._pump()
        rep = self._pick_handoff_target()
        self.telemetry.record_route(
            req=rid, replica=rep.name, policy="handoff",
            tenant=tenant, matched_blocks=int(pkg.n_blocks),
            outstanding=rep.inflight,
            **({"trace": ctx.trace_id} if ctx is not None else {}))
        rep.inflight += 1
        rep.routed += 1
        try:
            out = await rep.inst.admit_prefilled(pkg)
            self.handoffs += 1
            return out
        finally:
            rep.inflight -= 1
            self._pump()

    async def _dispatch(self, rep: ReplicaHandle, arr, tenant,
                        sampling, t_submit: float, fut,
                        ctx=None, rid: int = -1) -> None:
        released = False
        try:
            out = await rep.inst(arr, sampling=sampling,
                                 tenant=tenant, enqueue_ts=t_submit,
                                 trace=ctx)
            if isinstance(out, HandoffCursor):
                # prefill replica parked the request and freed its
                # slot — release stage-one capacity NOW, before the
                # decode leg, or the prefill fleet would stall for
                # the whole generation
                rep.inflight -= 1
                released = True
                self._pump()
                out = await self._forward_handoff(out, tenant, ctx,
                                                  rid)
            if not fut.done():
                fut.set_result(out)
        except Exception as e:  # noqa: BLE001 - surface to caller
            if not fut.done():
                fut.set_exception(e)
        finally:
            if not released:
                rep.inflight -= 1
                self._pump()

    # -- drain ---------------------------------------------------------

    async def drain(self, rep: ReplicaHandle,
                    timeout_s: float = 30.0) -> Dict[str, Any]:
        """Gracefully drain one replica: stop admitting (the dispatch
        loop skips draining replicas), wait for in-flight requests to
        finish, and verify the engine freed every KV block.  Journals
        a `drain` event; the caller shuts the engine down."""
        rep.draining = True
        n0 = rep.inflight
        deadline = time.perf_counter() + timeout_s
        while rep.inflight > 0 and time.perf_counter() < deadline:
            await asyncio.sleep(0.002)
        stats = rep.engine_stats()
        kv = stats.get("kv_cache") or {}
        blocks = int(kv.get("blocks_in_use", 0))
        ok = rep.inflight == 0 and blocks == 0
        self.telemetry.record_drain(rep.name, ok,
                                    blocks_in_use=blocks,
                                    drained_requests=n0)
        return {"replica": rep.name, "ok": ok,
                "blocks_in_use": blocks, "drained_requests": n0}

    def stats(self) -> Dict[str, Any]:
        return {
            "policy": self.policy,
            "wfq": self._wfq is not None,
            "queue_depth": self.queue_depth(),
            "inflight": self.total_inflight(),
            "routed_by_policy": dict(self.routed_by_policy),
            "disaggregated": self.disaggregated,
            "handoffs": self.handoffs,
            "requeued_on_death": self.requeued_on_death,
            "max_inflight_per_replica": self._cap,
            "tenants": {n: {"weight": t.weight,
                            "objective": t.objective,
                            "targets_ms": t.objectives()}
                        for n, t in self.tenants.items()},
        }


#: live fleets by name — the dashboard's /api/serve/fleet surface
#: (in-process direct-instance fleets: bench, tests, notebooks)
_FLEETS: Dict[str, "LLMFleet"] = {}


def fleet_registry() -> Dict[str, "LLMFleet"]:
    return dict(_FLEETS)


class LLMFleet:
    """N continuous-engine replicas + router + autoscaler, one object.

    Replicas are in-process engine instances from `factory` (all equal
    configs, so the module-level jit cache compiles each program
    once).  `await fleet(prompt, tenant=...)` routes a request;
    `await fleet.autoscale_step()` runs one control-loop tick."""

    def __init__(self, factory: Callable[[], Any], num_replicas: int,
                 *, name: str = "llm_fleet", block_size: int = 16,
                 tenants: Optional[Sequence[TenantClass]] = None,
                 policy: str = "prefix", wfq: bool = True,
                 autoscale: Optional[AutoscalePolicy] = None,
                 max_inflight_per_replica: Optional[int] = None,
                 seed: int = 0,
                 prefill_factory: Optional[Callable[[], Any]] = None,
                 num_prefill_replicas: int = 0,
                 health: Optional[HealthConfig] = None,
                 chaos: Optional[ChaosConfig] = None):
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        if (prefill_factory is None) != (num_prefill_replicas == 0):
            raise ValueError(
                "prefill_factory and num_prefill_replicas must be "
                "given together (a disaggregated fleet needs both)")
        self.name = name
        self._factory = factory
        self._prefill_factory = prefill_factory
        self.telemetry = EngineTelemetry(name)
        # healthwatch: one monitor per fleet, journaling into the
        # fleet flight recorder; RAYTPU_HEALTHWATCH=0 disables it
        # entirely (self.health is None, engines get no attach)
        self.health = (HealthMonitor(
            health, deployment=name,
            recorder=self.telemetry.flightrec)
            if healthwatch_enabled() else None)
        # chaos: inert unless the caller hands a ChaosConfig — the
        # default fleet attaches nothing to the engine loops
        self.chaos = (ChaosInjector(chaos, monitor=self.health)
                      if chaos is not None else None)
        self._replicas: List[ReplicaHandle] = []
        self._retired: List[ReplicaHandle] = []
        self._next_replica = itertools.count()
        self._next_prefill = itertools.count()
        self.autoscale_policy = autoscale or AutoscalePolicy()
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None
        # prefill replicas first so fleet listings read topology order
        for _ in range(int(num_prefill_replicas)):
            self._add_replica(prefill=True)
        for _ in range(num_replicas):
            self._add_replica()
        self.router = LLMRouter(
            self._replicas, block_size=block_size, tenants=tenants,
            policy=policy, wfq=wfq,
            max_inflight_per_replica=max_inflight_per_replica,
            seed=seed, telemetry=self.telemetry, name=name,
            health=self.health, chaos=self.chaos)
        _FLEETS[name] = self

    # -- replica lifecycle ---------------------------------------------

    @property
    def num_replicas(self) -> int:
        return len([r for r in self._replicas if not r.draining])

    def _add_replica(self, prefill: bool = False) -> ReplicaHandle:
        if prefill:
            rep = ReplicaHandle(
                f"{self.name}/p{next(self._next_prefill)}",
                self._prefill_factory())
        else:
            rep = ReplicaHandle(
                f"{self.name}/r{next(self._next_replica)}",
                self._factory())
        self._replicas.append(rep)
        # healthwatch attach — covers autoscale-added replicas too.
        # The engine heartbeats under its fleet name, and the monitor
        # watches its telemetry for token-silent residents.
        inst = rep.inst
        if hasattr(inst, "_replica_label"):
            inst._replica_label = rep.name
        if self.health is not None and hasattr(inst, "_health"):
            inst._health = self.health
            self.health.register(
                rep.name, role=rep.role,
                recorder=getattr(getattr(inst, "_telemetry", None),
                                 "flightrec", None),
                telemetry=getattr(inst, "_telemetry", None))
        if self.chaos is not None and hasattr(inst, "_chaos"):
            inst._chaos = self.chaos
            self.chaos.bind(rep.name)
        return rep

    async def __call__(self, prompt, tenant: Optional[str] = None,
                       sampling=None):
        return await self.router.submit(prompt, tenant=tenant,
                                        sampling=sampling)

    # -- autoscaling ---------------------------------------------------

    def _signals(self) -> Dict[str, float]:
        live = [r for r in self._replicas if not r.draining]
        burn = 0.0
        for rep in live:
            slo = getattr(rep.inst, "_telemetry", None)
            slo = getattr(slo, "slo", None)
            if slo is not None:
                burn = max(burn, worst_burn_rate(slo.snapshot()))
        backlog = self.router.queue_depth()
        per_rep = backlog / max(1, len(live))
        return {"burn_rate": round(burn, 4),
                "queue_depth": backlog,
                "queue_per_replica": round(per_rep, 4),
                "inflight": self.router.total_inflight()}

    async def autoscale_step(self, now: Optional[float] = None
                             ) -> Optional[Dict[str, Any]]:
        """One control-loop tick: read burn-rate + queue-depth
        signals, apply the policy (sustain windows, cooldowns, min/max
        bounds), and act — returns the action dict when the fleet
        scaled, else None.  `now` is injectable for deterministic
        tests; scale-down AWAITS the victim's graceful drain so a
        returned "down" action implies zero lost requests and zero
        resident KV blocks."""
        p = self.autoscale_policy
        now = time.perf_counter() if now is None else now
        sig = self._signals()
        n = self.num_replicas
        reason = None
        if sig["burn_rate"] > p.burn_threshold:
            reason, value = "burn_rate", sig["burn_rate"]
        elif sig["queue_per_replica"] > p.queue_high:
            reason, value = "queue_depth", sig["queue_per_replica"]
        if reason is not None:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
            sustained = now - self._breach_since >= p.sustain_s
            cooled = (self._last_up is None
                      or now - self._last_up >= p.up_cooldown_s)
            if sustained and cooled and n < p.max_replicas:
                self._add_replica()
                self._breach_since = None
                self._last_up = now
                self.telemetry.record_scale(
                    "up", n, n + 1, reason, signal=value)
                self.router._pump()
                return {"action": "up", "reason": reason,
                        "signal": value, "n_replicas": n + 1}
            return None
        idle = (sig["queue_depth"] == 0 and sig["inflight"] == 0
                and sig["burn_rate"] <= p.burn_threshold)
        if not idle:
            self._breach_since = None
            self._idle_since = None
            return None
        self._breach_since = None
        if self._idle_since is None:
            self._idle_since = now
        sustained = now - self._idle_since >= p.idle_s
        cooled = (self._last_down is None
                  or now - self._last_down >= p.down_cooldown_s)
        if not (sustained and cooled and n > p.min_replicas):
            return None
        live = [r for r in self._replicas if not r.draining]
        # never drain the prefill fleet on idle — role counts are the
        # operator's chip-split decision, not an autoscaler signal
        decodable = [r for r in live if r.role != "prefill"] or live
        victim = min(reversed(decodable), key=lambda r: r.inflight)
        idle_for = now - self._idle_since
        self._idle_since = None
        self._last_down = now
        self.telemetry.record_scale(
            "down", n, n - 1, "idle", signal=idle_for,
            replica=victim.name)
        drain = await self.router.drain(victim)
        self._replicas.remove(victim)
        self._retired.append(victim)
        if self.health is not None:
            # a drained replica stops heartbeating by design — drop
            # it from the monitor so retirement never reads as death
            self.health.unregister(victim.name)
        victim.inst.shutdown_engine()
        self.router._pump()
        return {"action": "down", "reason": "idle",
                "n_replicas": n - 1, "drain": drain}

    # -- reporting -----------------------------------------------------

    def tenant_report(self) -> Dict[str, Any]:
        """Per-tenant SLO attainment over every request the fleet has
        served (live + retired replicas): for each tenant objective
        with a target, the fraction of samples within target plus
        p50/p95 — the numbers bench/sweep publish as
        `{tenant}_{obj}_slo_attainment`."""
        out: Dict[str, Any] = {}
        reps = self._replicas + self._retired
        for tc in self.router.tenants.values():
            merged: Dict[str, List[float]] = {}
            for rep in reps:
                tele = getattr(rep.inst, "_telemetry", None)
                if tele is None:
                    continue
                for obj, series in tele.slo_samples(
                        tenant=tc.name).items():
                    merged.setdefault(obj, []).extend(
                        v for _ts, v in series)
            objectives = {}
            for obj, target in tc.objectives().items():
                vals = merged.get(obj, [])
                ok = sum(1 for v in vals if v <= target)
                objectives[obj] = {
                    "target_ms": target,
                    "samples": len(vals),
                    "attainment": round(ok / len(vals), 4)
                    if vals else None,
                    "latency_ms": _core.summarize(vals),
                }
            out[tc.name] = {
                "weight": tc.weight,
                "objective": tc.objective,
                "requests": len(merged.get("e2e", [])),
                "objectives": objectives,
            }
        return out

    def fleet_stats(self) -> Dict[str, Any]:
        """The dashboard /api/serve/fleet document: router counters,
        autoscaler state, per-replica engine summaries, and the
        fleet-wide prefix hit rate (pooled over replicas)."""
        hits = misses = 0
        chunks = {"requests": 0, "chunks": 0, "tokens": 0,
                  "max_chunks_per_request": 0}
        # kvscope pooling: waste counters SUM over replicas (each
        # replica's pager thrashes independently), occupancy reports
        # per-replica ratios plus the fleet max/mean — a fleet-wide
        # average would hide one replica's pool running hot
        scope = {"reprefill_waste_tokens": 0, "reprefill_events": 0,
                 "keys_evicted": 0, "prefill_tokens": 0,
                 "tier_hits": 0, "tokens_restored": 0}
        # host-tier pooling (serve/kv_tier.py): counters SUM over
        # replicas (each replica spills/restores its own tier), the
        # pooled hit rate is recomputed over the summed probes
        tier = {"hits": 0, "misses": 0, "saves": 0, "evictions": 0,
                "tokens_restored": 0, "bytes_resident": 0,
                "bytes_budget": 0, "entries": 0, "h2d_ms": 0.0,
                "d2h_ms": 0.0}
        tier_enabled = False
        waste_by_tenant: Dict[str, int] = {}
        occ_by_replica: Dict[str, float] = {}
        occ_p95s: List[float] = []
        # role-aware occupancy pooling: a decode pool's occupancy is a
        # capacity signal (whole resident chains), a prefill pool's is
        # churn (blocks park in the LRU the moment a handoff leaves) —
        # averaging them together would report a meaningless blend
        occ_by_role: Dict[str, List[float]] = {}
        occ_p95_by_role: Dict[str, List[float]] = {}
        handoff = {"handoffs_out": 0, "handoffs_in": 0,
                   "blocks_moved": 0, "fast_path": 0, "staged": 0,
                   "requeues": 0}
        replicas = {}
        for rep in self._replicas + self._retired:
            st = rep.engine_stats()
            for k, v in (st.get("handoff") or {}).items():
                if k in handoff:
                    handoff[k] += int(v)
            kv = st.get("kv_cache") or {}
            hits += int(kv.get("prefix_block_hits", 0))
            misses += int(kv.get("prefix_block_misses", 0))
            pc = st.get("prefill_chunks") or {}
            for k in ("requests", "chunks", "tokens"):
                chunks[k] += int(pc.get(k, 0))
            chunks["max_chunks_per_request"] = max(
                chunks["max_chunks_per_request"],
                int(pc.get("max_chunks_per_request", 0)))
            ks = st.get("kv_scope") or {}
            forensics = ks.get("forensics") or {}
            for k in scope:
                scope[k] += int(forensics.get(k, 0))
            for t, v in (forensics.get("waste_by_tenant")
                         or {}).items():
                waste_by_tenant[t] = waste_by_tenant.get(t, 0) + int(v)
            occ = ks.get("occupancy") or {}
            occ_by_replica[rep.name] = float(
                occ.get("occupancy_ratio", 0.0))
            occ_p95s.append(float(occ.get("occupancy_p95", 0.0)))
            occ_by_role.setdefault(rep.role, []).append(float(
                occ.get("occupancy_ratio", 0.0)))
            occ_p95_by_role.setdefault(rep.role, []).append(float(
                occ.get("occupancy_p95", 0.0)))
            kt = st.get("kv_tier") or {}
            if kt.get("enabled"):
                tier_enabled = True
            for k in tier:
                tier[k] = round(tier[k] + (kt.get(k) or 0), 3) \
                    if k.endswith("_ms") else tier[k] + int(kt.get(k)
                                                           or 0)
            replicas[rep.name] = {
                "role": rep.role,
                "draining": rep.draining,
                "retired": rep in self._retired,
                "inflight": rep.inflight,
                "routed": rep.routed,
                "requests": st.get("requests"),
                "kv_cache": kv,
                "handoff": st.get("handoff"),
                "slo_breached": (st.get("slo") or {}).get("breached")
                if st.get("slo") else None,
            }
        total = hits + misses
        occ_vals = list(occ_by_replica.values())
        kv_scope = dict(
            scope,
            reprefill_waste_frac=round(
                scope["reprefill_waste_tokens"]
                / scope["prefill_tokens"], 4)
            if scope["prefill_tokens"] else 0.0,
            waste_by_tenant=waste_by_tenant,
            occupancy_by_replica=occ_by_replica,
            occupancy_max=max(occ_vals) if occ_vals else 0.0,
            occupancy_mean=round(sum(occ_vals) / len(occ_vals), 4)
            if occ_vals else 0.0,
            # worst replica's ring p95 — the fleet headline occupancy
            # number (an average would hide one pool running hot)
            occupancy_p95=max(occ_p95s) if occ_p95s else 0.0,
            occupancy_by_role={
                role: {
                    "mean": round(sum(vals) / len(vals), 4),
                    "max": max(vals),
                    "p95": max(occ_p95_by_role.get(role) or [0.0]),
                }
                for role, vals in occ_by_role.items() if vals})
        tier_probes = tier["hits"] + tier["misses"]
        kv_tier = dict(
            tier, enabled=tier_enabled,
            hit_rate=round(tier["hits"] / tier_probes, 4)
            if tier_probes else 0.0)
        return {
            "name": self.name,
            "num_replicas": self.num_replicas,
            "router": self.router.stats(),
            "autoscale": dataclasses.asdict(self.autoscale_policy),
            "signals": self._signals(),
            "prefix_hit_rate": round(hits / total, 4) if total
            else 0.0,
            "prefill_chunks": chunks,
            "kv_scope": kv_scope,
            "kv_tier": kv_tier,
            "handoff": handoff,
            "tenants": self.tenant_report(),
            "replicas": replicas,
            "health": self._health_block(),
            "flightrec": self.telemetry.flightrec.stats(),
            "latency_anatomy": self.latency_anatomy(),
        }

    def _health_block(self) -> Dict[str, Any]:
        """Fleet health block — zeroed (enabled=False) when the
        monitor is off, so /api/serve/health consumers never branch
        on presence."""
        if self.health is None:
            return empty_fleet_health()
        block = self.health.fleet_block()
        block["requeued_on_death"] = self.router.requeued_on_death
        if self.chaos is not None:
            block["chaos"] = self.chaos.stats()
        return block

    # -- tracebus (tools/tracebus.py collects these) -------------------

    def anatomy_samples(self, tenant: Optional[str] = None
                        ) -> Dict[str, Any]:
        """Raw latency-anatomy samples pooled over every replica (live
        and retired) — fleet percentiles come from the union of
        per-request samples, never from averaged summaries."""
        parts = []
        for rep in self._replicas + self._retired:
            fn = getattr(rep.inst, "anatomy_samples", None)
            if fn is not None:
                parts.append(fn(tenant=tenant))
        return merge_anatomy_samples(parts)

    def latency_anatomy(self) -> Dict[str, Any]:
        """Fleet-wide ITL/TPOT percentiles + critical-path
        decomposition, overall and per tenant (fleet_stats block)."""
        samples = self.anatomy_samples()
        by_tenant = {
            t: latency_anatomy(self.anatomy_samples(tenant=t))
            for t in samples["tenants"]}
        return dict(latency_anatomy(samples), by_tenant=by_tenant)

    def trace_records(self) -> List[Dict[str, Any]]:
        """Tracebus request snapshots from every replica (replica
        lane name attached)."""
        out: List[Dict[str, Any]] = []
        for rep in self._replicas + self._retired:
            fn = getattr(rep.inst, "trace_records", None)
            if fn is None:
                continue
            for snap in fn():
                snap["replica"] = rep.name
                out.append(snap)
        return out

    def find_request(self, request_id) -> Optional[Dict[str, Any]]:
        """Locate one request across replicas by trace id (or
        engine-local id); None when no replica knows it."""
        for rep in self._replicas + self._retired:
            fn = getattr(rep.inst, "request_trace", None)
            if fn is None:
                continue
            snap = fn(request_id)
            if snap is not None:
                snap["replica"] = rep.name
                return snap
        return None

    def shutdown(self) -> None:
        """Stop every engine (live and retired) and deregister."""
        for rep in self._replicas + self._retired:
            try:
                rep.inst.shutdown_engine()
            except Exception:  # noqa: BLE001 - already dead
                pass
        _FLEETS.pop(self.name, None)


def build_llm_fleet(family: str = "gpt2", preset: str = "nano", *,
                    num_replicas: int = 2,
                    num_prefill_replicas: Optional[int] = None,
                    num_decode_replicas: Optional[int] = None,
                    prefill_engine_kw: Optional[Dict[str, Any]] = None,
                    decode_engine_kw: Optional[Dict[str, Any]] = None,
                    handoff_staged: bool = False,
                    tenants: Optional[Sequence[TenantClass]] = None,
                    routing: str = "prefix", wfq: bool = True,
                    autoscale: Optional[AutoscalePolicy] = None,
                    max_inflight_per_replica: Optional[int] = None,
                    fleet_name: Optional[str] = None, seed: int = 0,
                    health: Optional[HealthConfig] = None,
                    chaos: Optional[ChaosConfig] = None,
                    **engine_kw) -> LLMFleet:
    """Stand up independent continuous-engine replicas (each its own
    jitted programs / BlockPager / SLOTracker) behind an `LLMRouter`.
    `engine_kw` is forwarded to `build_llm_deployment`; the continuous
    scheduler and paged KV layout are forced on (prefix routing needs
    the pager's key export — a dense-layout fleet would route by load
    only).  `max_inflight_per_replica` defaults to the engine's
    `max_slots`, keeping any backlog at the router where WFQ can
    reorder it.

    Homogeneous by default (`num_replicas` role="both" engines).
    Setting BOTH `num_prefill_replicas` and `num_decode_replicas`
    builds a DISAGGREGATED fleet instead: role-typed replica sets with
    block-granular KV handoff (docs/serve.md#disaggregated-serving) —
    the router admits to the least-loaded prefill replica, the prefill
    engine exports the filled block rows at last-chunk completion, and
    a decode replica chosen by free-block headroom splices them in and
    finishes the generation.  `prefill_engine_kw` / `decode_engine_kw`
    overlay per-role engine knobs (mesh degree, batch shape, slot
    count: `mesh`, `prefill_bucket`, `max_slots`, `kv_num_blocks`, …)
    on top of the shared `engine_kw`; `kv_block_size` must stay equal
    across roles — the handoff moves whole blocks.  `handoff_staged`
    forces the D2H→H2D host-staging hop (the cross-process path) even
    in-process.  `spec_decode` applies to decode replicas only
    (drafting is decode-side work)."""
    from ray_tpu.serve.llm import build_llm_deployment

    engine_kw.setdefault("scheduler", "continuous")
    engine_kw.setdefault("kv_layout", "paged")
    name = fleet_name or f"fleet_{family}_{preset}"
    disagg = (num_prefill_replicas is not None
              or num_decode_replicas is not None)
    if disagg:
        if not (num_prefill_replicas and num_decode_replicas):
            raise ValueError(
                "a disaggregated fleet needs BOTH "
                "num_prefill_replicas and num_decode_replicas >= 1, "
                f"got {num_prefill_replicas}/{num_decode_replicas}")
        pre_kw = dict(engine_kw)
        pre_kw.update(prefill_engine_kw or {})
        # drafting is decode-side work; the prefill replica's first
        # token is the same with or without a draft model
        pre_kw.pop("spec_decode", None)
        pre_kw.update(role="prefill", handoff_staged=handoff_staged)
        dec_kw = dict(engine_kw)
        dec_kw.update(decode_engine_kw or {})
        dec_kw["role"] = "decode"
        bs_pre = int(pre_kw.get("kv_block_size", 16))
        bs_dec = int(dec_kw.get("kv_block_size", 16))
        if bs_pre != bs_dec:
            raise ValueError(
                "kv_block_size must match across roles (the handoff "
                f"moves whole blocks), got prefill={bs_pre} "
                f"decode={bs_dec}")
        pre_dep = build_llm_deployment(family, preset, **pre_kw)
        dec_dep = build_llm_deployment(family, preset, **dec_kw)
        if max_inflight_per_replica is None:
            max_inflight_per_replica = int(dec_kw.get("max_slots", 4))
        return LLMFleet(
            dec_dep.func_or_class, int(num_decode_replicas),
            prefill_factory=pre_dep.func_or_class,
            num_prefill_replicas=int(num_prefill_replicas),
            name=name, block_size=bs_dec, tenants=tenants,
            policy=routing, wfq=wfq, autoscale=autoscale,
            max_inflight_per_replica=max_inflight_per_replica,
            seed=seed, health=health, chaos=chaos)
    max_slots = int(engine_kw.get("max_slots", 4))
    if max_inflight_per_replica is None:
        max_inflight_per_replica = max_slots
    dep = build_llm_deployment(family, preset, **engine_kw)
    return LLMFleet(
        dep.func_or_class, num_replicas,
        name=name,
        block_size=int(engine_kw.get("kv_block_size", 16)),
        tenants=tenants, policy=routing, wfq=wfq,
        autoscale=autoscale,
        max_inflight_per_replica=max_inflight_per_replica, seed=seed,
        health=health, chaos=chaos)
