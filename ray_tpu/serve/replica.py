"""Replica actor: hosts one copy of a deployment's callable.

Reference analog: serve/_private/replica.py:250 RayServeReplica (:494
handle_request).  The user object is constructed once per replica; sync
callables run on the actor's concurrency slots (max_concurrency >1 gives
intra-replica parallelism, the analog of max_concurrent_queries).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import cloudpickle


class RayServeReplica:
    def __init__(self, serialized_def: bytes, init_args: tuple,
                 init_kwargs: Dict[str, Any], deployment_name: str):
        target = cloudpickle.loads(serialized_def)
        self.deployment_name = deployment_name
        if isinstance(target, type):
            self.callable = target(*init_args, **init_kwargs)
        else:
            self.callable = target
        self.num_requests = 0
        self._ongoing = 0
        self._mu = threading.Lock()
        self.started_at = time.time()

    def handle_request(self, *args, _serve_method: str = "__call__",
                       **kwargs):
        with self._mu:
            self.num_requests += 1
            self._ongoing += 1
        try:
            fn = self.callable if _serve_method == "__call__" and \
                callable(self.callable) else getattr(self.callable,
                                                     _serve_method)
            return fn(*args, **kwargs)
        finally:
            with self._mu:
                self._ongoing -= 1

    def ongoing_requests(self) -> int:
        """Autoscaling signal (reference: replica queue metrics feeding
        autoscaling_policy.py:127)."""
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        return {"deployment": self.deployment_name,
                "num_requests": self.num_requests,
                "ongoing": self._ongoing,
                "uptime_s": time.time() - self.started_at}

    def ping(self) -> bool:
        return True
