"""Replica actor: hosts one copy of a deployment's callable.

Reference analog: serve/_private/replica.py:250 RayServeReplica (:494
handle_request).  The user object is constructed once per replica; sync
callables run on the actor's concurrency slots (max_concurrency >1 gives
intra-replica parallelism, the analog of max_concurrent_queries).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import cloudpickle


class DeploymentHandleMarker:
    """Placeholder for a bound sub-deployment inside init args — the
    deployment-graph edge (reference: serve/deployment_graph.py nodes).
    Resolved to a live DeploymentHandle at replica construction."""

    def __init__(self, name: str):
        self.name = name


def _resolve_markers(obj):
    if isinstance(obj, DeploymentHandleMarker):
        from ray_tpu.serve.api import get_deployment_handle

        return get_deployment_handle(obj.name)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_resolve_markers(x) for x in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v) for k, v in obj.items()}
    return obj


class RayServeReplica:
    def __init__(self, serialized_def: bytes, init_args: tuple,
                 init_kwargs: Dict[str, Any], deployment_name: str):
        target = cloudpickle.loads(serialized_def)
        self.deployment_name = deployment_name
        init_args = _resolve_markers(tuple(init_args))
        init_kwargs = _resolve_markers(dict(init_kwargs or {}))
        if isinstance(target, type):
            self.callable = target(*init_args, **init_kwargs)
        else:
            self.callable = target
        self.num_requests = 0
        self._ongoing = 0
        self._mu = threading.Lock()
        self.started_at = time.time()

    def handle_request(self, *args, _serve_method: str = "__call__",
                       **kwargs):
        with self._mu:
            self.num_requests += 1
            self._ongoing += 1
        try:
            fn = self.callable if _serve_method == "__call__" and \
                callable(self.callable) else getattr(self.callable,
                                                     _serve_method)
            return fn(*args, **kwargs)
        finally:
            with self._mu:
                self._ongoing -= 1

    def ongoing_requests(self) -> int:
        """Autoscaling signal (reference: replica queue metrics feeding
        autoscaling_policy.py:127)."""
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        return {"deployment": self.deployment_name,
                "num_requests": self.num_requests,
                "ongoing": self._ongoing,
                "uptime_s": time.time() - self.started_at}

    def ping(self) -> bool:
        return True
