"""Engine telemetry for the LM serving hot path.

Every request through ``serve/llm.py`` carries a lifecycle record —
enqueue → admit → prefill-done (first token) → per-decode-step →
finish / reject — and the continuous-batching engine reports each
transition here.  Three sinks hang off those records:

1. **util/metrics.py** Histograms / Counters / Gauges (TTFT, queue
   wait, inter-token latency, slot occupancy, queue depth,
   admissions/rejections, tokens, and a recompile counter keyed by
   prefill bucket) — published to the dashboard ``/metrics`` Prometheus
   page through the existing GCS-KV snapshot path, no new plumbing.
2. **engine_stats()** — an on-demand snapshot (p50/p95/p99 TTFT and
   queue wait, throughput, slot utilization, request counts) exposed as
   a deployment method and aggregated at ``/api/serve/stats``.
3. **export_timeline()** — a chrome-trace exporter rendering engine
   steps, per-slot occupancy lanes, and per-request spans in the same
   format as ``python -m ray_tpu timeline``, so engine activity and
   task activity open in one Perfetto view.

Everything is host-side bookkeeping (dict/deque appends plus a
histogram observe) timed around syncs the engine already performs; the
jitted prefill/decode programs are untouched and no device syncs are
added.  When ``util/tracing.py`` is enabled, each request records a
root span at enqueue and a child span at finish, linking the serve
request to its engine work.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
import uuid
from typing import Any, Deque, Dict, List, Optional

from ray_tpu._private import telemetry as _core
from ray_tpu._private.flightrec import FlightRecorder
from ray_tpu.serve.health import empty_health as _empty_health
from ray_tpu.serve.kv_tier import empty_kv_tier as _empty_kv_tier
from ray_tpu.serve.kvscope import empty_kv_scope as _empty_kv_scope
from ray_tpu.util import tracing

#: ms boundaries for request-level latencies (TTFT, queue wait, total)
_LATENCY_BOUNDS_MS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0, 10000.0)
#: ms boundaries for per-decode-step (inter-token) latency
_STEP_BOUNDS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0)

_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None

_roofline_cache: Optional[Dict[str, Any]] = None


def _device_roofline() -> Optional[Dict[str, Any]]:
    """This process's roofline constants (peak FLOPs, HBM bandwidth,
    ridge point), cached after first success — engine_stats() is called
    per scrape and the constants cannot change under a live backend.
    None when the lookup itself fails (stats must never raise)."""
    global _roofline_cache
    if _roofline_cache is None:
        try:
            from ray_tpu._private.device_stats import device_roofline

            _roofline_cache = device_roofline()
        except Exception:  # noqa: BLE001 - stats are best-effort
            return None
    return dict(_roofline_cache)


def _engine_metrics() -> Dict[str, Any]:
    """Process-wide metric singletons (one registration per name no
    matter how many deployments/telemetry instances this process hosts
    — the registry warns on duplicate names)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            tags = ("deployment",)
            _metrics = {
                "ttft": Histogram(
                    "serve_ttft_ms",
                    "time to first token (enqueue -> prefill sample)",
                    boundaries=_LATENCY_BOUNDS_MS, tag_keys=tags),
                "queue_wait": Histogram(
                    "serve_queue_wait_ms",
                    "request wait in the admission queue",
                    boundaries=_LATENCY_BOUNDS_MS, tag_keys=tags),
                "inter_token": Histogram(
                    "serve_inter_token_ms",
                    "pooled decode step walltime",
                    boundaries=_STEP_BOUNDS_MS, tag_keys=tags),
                "latency": Histogram(
                    "serve_request_latency_ms",
                    "request latency (enqueue -> finish)",
                    boundaries=_LATENCY_BOUNDS_MS, tag_keys=tags),
                "active_slots": Gauge(
                    "serve_active_slots",
                    "KV slots decoding this engine step", tag_keys=tags),
                "queue_depth": Gauge(
                    "serve_queue_depth",
                    "requests waiting for a slot", tag_keys=tags),
                "slot_utilization": Gauge(
                    "serve_slot_utilization",
                    "time-weighted active/max slot fraction",
                    tag_keys=tags),
                "tokens_per_sec": Gauge(
                    "serve_tokens_per_sec",
                    "decode throughput over the step window",
                    tag_keys=tags),
                "admitted": Counter(
                    "serve_requests_admitted_total",
                    "requests admitted into a slot", tag_keys=tags),
                "finished": Counter(
                    "serve_requests_finished_total",
                    "requests finished", tag_keys=tags),
                "rejected": Counter(
                    "serve_requests_rejected_total",
                    "requests rejected at admission, labeled by reason "
                    "(oversized / shed_* / invalid)",
                    tag_keys=("deployment", "reason")),
                "errors": Counter(
                    "serve_requests_errored_total",
                    "requests failed by an engine error", tag_keys=tags),
                "tokens": Counter(
                    "serve_tokens_generated_total",
                    "decode tokens sampled", tag_keys=tags),
                "prefill_compiles": Counter(
                    "serve_prefill_compiles_total",
                    "first-seen prefill bucket shapes (one XLA compile "
                    "each)", tag_keys=("deployment", "bucket")),
                "program_compiles": Counter(
                    "serve_program_compile_events_total",
                    "XLA compile events by engine program name "
                    "(prefill / decode / sharded_decode / ...) — the "
                    "recompile counter beyond prefill buckets, fed by "
                    "the device_stats program registry",
                    tag_keys=("deployment", "program")),
                "prefix_hits": Counter(
                    "serve_prefix_blocks_hit_total",
                    "prompt KV blocks served from the prefix cache "
                    "(prefill skipped)", tag_keys=tags),
                "prefix_misses": Counter(
                    "serve_prefix_blocks_miss_total",
                    "prompt KV blocks that had to be prefilled",
                    tag_keys=tags),
                "cow_copies": Counter(
                    "serve_kv_cow_copies_total",
                    "copy-on-write forks of shared KV blocks",
                    tag_keys=tags),
                "kv_blocks_in_use": Gauge(
                    "serve_kv_blocks_in_use",
                    "pool blocks referenced by live sequences",
                    tag_keys=tags),
                "spec_proposed": Counter(
                    "serve_spec_tokens_proposed_total",
                    "draft tokens proposed to the spec-decode "
                    "verifier", tag_keys=tags),
                "spec_accepted": Counter(
                    "serve_spec_tokens_accepted_total",
                    "draft tokens the target model accepted",
                    tag_keys=tags),
                "spec_rounds": Counter(
                    "serve_spec_rounds_total",
                    "speculative propose+verify rounds (one target "
                    "dispatch each)", tag_keys=tags),
                "kv_occupancy": Gauge(
                    "serve_kv_occupancy_ratio",
                    "fraction of the usable KV pool (null block "
                    "excluded) held in-use or parked in the LRU "
                    "cache", tag_keys=tags),
                "kv_fragmentation": Gauge(
                    "serve_kv_fragmentation",
                    "largest-contiguous-free-run deficit of the KV "
                    "pool (0 = one contiguous run, ->1 = shattered)",
                    tag_keys=tags),
                "kv_reprefill_waste": Counter(
                    "serve_kv_reprefill_waste_tokens_total",
                    "prompt tokens re-prefilled into blocks whose "
                    "content key was previously resident and evicted "
                    "(residual churn the host-RAM KV tier did not "
                    "absorb)", tag_keys=tags),
                "kv_tier_bytes": Gauge(
                    "serve_kv_tier_bytes_resident",
                    "bytes of evicted KV blocks resident in the "
                    "host-RAM tier (serve/kv_tier.py)", tag_keys=tags),
                "kv_tier_hit_rate": Gauge(
                    "serve_kv_tier_hit_rate",
                    "fraction of host-tier second-chance probes that "
                    "restored a block via H2D copy", tag_keys=tags),
                "kv_tier_restored": Counter(
                    "serve_kv_tier_tokens_restored_total",
                    "prompt tokens re-admitted from the host tier "
                    "via H2D copy instead of re-prefill",
                    tag_keys=tags),
            }
        return _metrics


def _tracebus_enabled() -> bool:
    """Tracebus bookkeeping (TraceContext + per-token timestamps) is
    always-on unless ``RAYTPU_TRACEBUS=0`` — same opt-out contract as
    the flight recorder, and guarded by the same <5% overhead test."""
    return os.environ.get("RAYTPU_TRACEBUS", "1") != "0"


class TraceContext:
    """Causal identity of one request across router → engine → device.

    Born at ``LLMRouter.submit`` (or at engine enqueue for a request
    that never crossed a router) and threaded alongside the existing
    ``enqueue_ts`` backdating path, so every component that touches the
    request can stamp spans onto one object.  All timestamps are on the
    process monotonic clock (``time.perf_counter``) — the same domain
    as telemetry, flightrec, and the device observatory, which is what
    lets the tracebus collector merge all three onto a single timeline.

    Span ids are ``"<trace_id>:<n>"`` with ``:0`` reserved for the
    implicit request-root span, so parent/child stitching needs no
    shared counter beyond the context itself (requests are pumped from
    a single event loop; the int bump is not contended)."""

    __slots__ = ("trace_id", "origin", "spans", "_n")

    def __init__(self, origin: str = "engine",
                 trace_id: Optional[str] = None):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.origin = origin  # "router" | "engine"
        self.spans: List[Dict[str, Any]] = []
        self._n = 0

    @property
    def root_id(self) -> str:
        return f"{self.trace_id}:0"

    def span(self, name: str, start: float, end: float,
             parent: Optional[str] = None, **attrs: Any) -> str:
        self._n += 1
        sid = f"{self.trace_id}:{self._n}"
        self.spans.append({
            "name": name, "span_id": sid,
            "parent_id": parent or self.root_id,
            "start": float(start), "end": float(end), "attrs": attrs,
        })
        return sid


#: critical-path components; together with ``e2e_ms`` these are the
#: keys of every decomposition dict, and the components sum to
#: ``e2e_ms`` exactly (modulo float rounding) by construction.
CRITICAL_PATH_COMPONENTS = (
    "router_wait_ms", "queue_wait_ms", "requeue_ms", "kv_fetch_ms",
    "prefill_ms", "prefill_wait_ms", "handoff_ms", "inter_token_ms",
    "spec_rollback_ms")


def critical_path(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Decompose one completed request's e2e latency:

        e2e = router_wait + queue_wait + requeue + kv_fetch + prefill
              + prefill_wait + handoff + inter_token + spec_rollback

    * router_wait — submit → engine enqueue (0 without a router);
    * queue_wait  — engine enqueue → admit, minus time spent requeued
      and minus the kv_fetch window below;
    * requeue     — first KV-exhaustion requeue → eventual admit;
    * kv_fetch    — H2D restore of host-tier KV blocks during this
      admission (serve/kv_tier.py; exactly 0 without a tier hit);
    * prefill     — admit → first token, or for chunked-prefill
      admissions the SUM of the per-chunk dispatch windows;
    * prefill_wait — the rest of admit → first token: time a chunked
      prefill spent parked between chunks while decode waves ran
      (exactly 0 for one-shot prefill);
    * handoff     — disaggregated serving only: prefill-side KV
      export → decode-side block install (serve/router.py two-stage
      dispatch), carved out of the decode leg it delays (exactly 0
      for monolithic engines);
    * inter_token — Σ inter-token gaps (first token → finish), minus
      the estimated rollback share below and the handoff window;
    * spec_rollback — decode time attributed to rejected draft
      positions in speculative verify rounds.

    Timestamps are clamped into the [enqueue, finish] window so a
    record driven by a synthetic test clock degrades to zeros instead
    of negative components.  None for incomplete/failed records."""
    if rec.get("finish") is None or rec.get("status") != "ok":
        return None
    if rec.get("admit") is None or rec.get("first_token") is None:
        return None
    enq, fin = rec["enqueue"], rec["finish"]
    e2e = max(0.0, fin - enq)
    t_eng = rec.get("engine_enqueue")
    t_eng = enq if t_eng is None else min(max(t_eng, enq), fin)
    admit = min(max(rec["admit"], t_eng), fin)
    first = min(max(rec["first_token"], admit), fin)
    router_wait = t_eng - enq
    wait = admit - t_eng
    requeue = 0.0
    rq_ts = rec.get("requeue_ts")
    if rq_ts is not None:
        requeue = min(max(0.0, admit - rq_ts), wait)
    # host-tier restore: the H2D window is carved out of the queue
    # leg it ran inside (admission work before record_admit), clamped
    # like every other component so synthetic clocks degrade to 0
    kv_fetch = 0.0
    kf = rec.get("kv_fetch")
    if kf is not None:
        kv_fetch = min(max(0.0, min(float(kf[1]), admit)
                           - max(float(kf[0]), t_eng)),
                       wait - requeue)
    queue_wait = wait - requeue - kv_fetch
    window = first - admit
    chunks = rec.get("prefill_chunks")
    if chunks:
        # chunked prefill: the prefill leg is the sum of the chunk
        # dispatch windows (clamped into [admit, first] so synthetic
        # clocks degrade gracefully); the residual of admit → first is
        # the parked time between chunks — decode waves ran there, so
        # it must not be billed as prefill compute
        prefill = min(window, sum(
            max(0.0, min(float(c[1]), first) - max(float(c[0]), admit))
            for c in chunks))
        prefill_wait = window - prefill
    else:
        prefill = window
        prefill_wait = 0.0
    decode = fin - first
    rollback = min(max(0.0, float(rec.get("spec_rollback_s") or 0.0)),
                   decode)
    # disaggregated handoff: the export→install window sits between
    # the prefill replica's first token and the decode replica's first
    # decode wave, so it is carved out of the decode leg it delayed
    # (clamped into [first, finish] like every other component)
    handoff = 0.0
    kh = rec.get("kv_handoff")
    if kh is not None:
        handoff = min(max(0.0, min(float(kh[1]), fin)
                          - max(float(kh[0]), first)),
                      decode - rollback)
    ms = 1e3
    return {
        "e2e_ms": round(e2e * ms, 4),
        "router_wait_ms": round(router_wait * ms, 4),
        "queue_wait_ms": round(queue_wait * ms, 4),
        "requeue_ms": round(requeue * ms, 4),
        "kv_fetch_ms": round(kv_fetch * ms, 4),
        "prefill_ms": round(prefill * ms, 4),
        "prefill_wait_ms": round(prefill_wait * ms, 4),
        "handoff_ms": round(handoff * ms, 4),
        "inter_token_ms": round((decode - rollback - handoff) * ms, 4),
        "spec_rollback_ms": round(rollback * ms, 4),
    }


def _token_gaps_ms(rec: Dict[str, Any]) -> List[float]:
    """Inter-token gaps (ms) from the per-token timestamp trail.
    Tokens emitted by one spec-verify dispatch share a timestamp, so
    their intra-round gaps are 0 — the single-dispatch reality."""
    ts = rec.get("token_ts")
    if not ts or len(ts) < 2:
        return []
    return [(b - a) * 1e3 for a, b in zip(ts, ts[1:])]


def request_snapshot(rec: Dict[str, Any],
                     deployment: Optional[str] = None
                     ) -> Dict[str, Any]:
    """Plain JSON-able view of one lifecycle record for the tracebus
    collector: hop timestamps, the token trail, router-side spans from
    the TraceContext, and the derived critical-path decomposition."""
    ctx = rec.get("ctx")
    kv = rec.get("kv_reserve")
    return {
        "request": (ctx.trace_id if ctx is not None
                    else f"req{rec['id']}"),
        "trace_id": ctx.trace_id if ctx is not None else None,
        "origin": ctx.origin if ctx is not None else "engine",
        "id": rec["id"],
        "deployment": deployment,
        "tenant": rec.get("tenant"),
        "status": rec.get("status"),
        "prompt_len": rec.get("prompt_len"),
        "tokens": rec.get("tokens", 0),
        "bucket": rec.get("bucket"),
        "slot": rec.get("slot"),
        "enqueue": rec.get("enqueue"),
        "engine_enqueue": rec.get("engine_enqueue"),
        "admit": rec.get("admit"),
        "first_token": rec.get("first_token"),
        "finish": rec.get("finish"),
        "token_ts": (list(rec["token_ts"])
                     if rec.get("token_ts") else None),
        "requeues": rec.get("requeues", 0),
        "requeue_ts": rec.get("requeue_ts"),
        "spec_rounds": rec.get("spec_rounds", 0),
        "spec_proposed": rec.get("spec_proposed", 0),
        "spec_accepted": rec.get("spec_accepted", 0),
        "spec_rollback_s": rec.get("spec_rollback_s", 0.0),
        "kv_reserve": list(kv) if kv is not None else None,
        "kv_fetch": (list(rec["kv_fetch"])
                     if rec.get("kv_fetch") is not None else None),
        "kv_handoff": (list(rec["kv_handoff"])
                       if rec.get("kv_handoff") is not None else None),
        "prefill_chunks": ([list(c) for c in rec["prefill_chunks"]]
                           if rec.get("prefill_chunks") else None),
        "spans": ([dict(s) for s in ctx.spans]
                  if ctx is not None else []),
        "critical_path": critical_path(rec),
        "itl_ms": _token_gaps_ms(rec),
    }


def empty_anatomy_samples() -> Dict[str, Any]:
    return {"itl_ms": [], "tpot_ms": [], "ttft_ms": [],
            "critical_path": {k: [] for k in
                              ("e2e_ms",) + CRITICAL_PATH_COMPONENTS},
            "tenants": []}


def merge_anatomy_samples(parts: List[Dict[str, Any]]
                          ) -> Dict[str, Any]:
    """Pool raw latency-anatomy samples across engines (fleet_stats
    aggregates replicas this way so fleet percentiles are computed
    over the union, not averaged per-replica summaries)."""
    out = empty_anatomy_samples()
    tenants: set = set()
    for p in parts:
        if not p:
            continue
        out["itl_ms"].extend(p.get("itl_ms", ()))
        out["tpot_ms"].extend(p.get("tpot_ms", ()))
        out["ttft_ms"].extend(p.get("ttft_ms", ()))
        for k, vals in p.get("critical_path", {}).items():
            out["critical_path"].setdefault(k, []).extend(vals)
        tenants.update(p.get("tenants", ()))
    out["tenants"] = sorted(tenants)
    return out


def latency_anatomy(samples: Dict[str, Any]) -> Dict[str, Any]:
    """Summarize raw anatomy samples into the stable
    ``engine_stats()["latency_anatomy"]`` shape (sans by_tenant)."""
    return {
        "requests": len(samples["critical_path"]["e2e_ms"]),
        "itl_ms": _core.summarize(samples["itl_ms"]),
        "tpot_ms": _core.summarize(samples["tpot_ms"]),
        "ttft_ms": _core.summarize(samples["ttft_ms"]),
        "critical_path": {k: _core.summarize(v) for k, v
                          in samples["critical_path"].items()},
    }


class EngineTelemetry:
    """Lifecycle recorder for one engine (deployment replica or bench
    harness).  All methods take an optional ``now`` (seconds, from
    ``time.perf_counter()``) so tests can drive deterministic clocks;
    production callers omit it."""

    def __init__(self, deployment: str, max_slots: int = 0,
                 history: int = 4096, role: str = "both"):
        self.deployment = deployment
        self.max_slots = int(max_slots)
        #: disaggregated serving role ("prefill" | "decode" | "both");
        #: surfaced as engine_stats()["role"] so fleet pooling can
        #: keep decode-pool occupancy apart from prefill pools
        self.role = str(role)
        self._m = _engine_metrics()
        self._tags = {"deployment": deployment}
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._t0 = time.perf_counter()
        #: retired request records (finished / rejected / errored)
        self._done: Deque[Dict[str, Any]] = collections.deque(
            maxlen=history)
        #: (end_ts, dur_s, n_tokens) per pooled decode step (n_tokens
        #: == n_active except spec-decode rounds, which emit several
        #: tokens per slot per dispatch)
        self._steps: Deque[tuple] = collections.deque(maxlen=history)
        self._active: Dict[int, Dict[str, Any]] = {}
        self._counts = {"enqueued": 0, "admitted": 0, "finished": 0,
                        "rejected": 0, "errors": 0}
        self._queue_depth = 0
        self._max_active = 0
        self._n_steps = 0
        self._tokens = 0
        self._busy_slot_s = 0.0     # sum(active * dur) over steps
        self._step_s = 0.0          # sum(dur) over steps
        self._buckets: Dict[int, int] = {}  # prefill bucket -> admits
        self._program_compiles: Dict[str, int] = {}
        self._rejections_by_reason: Dict[str, int] = {}
        self._kv_stats: Optional[Dict[str, Any]] = None
        #: kvscope block (serve/kvscope.py) the deployment composes —
        #: occupancy ring + eviction forensics + HBM ledger; the
        #: waste counter below tracks how much of the cumulative
        #: reprefill_waste_tokens has already been pushed to the
        #: Prometheus counter (counters take deltas, stats are totals)
        self._kv_scope: Optional[Dict[str, Any]] = None
        self._kv_waste_reported = 0
        #: host-RAM KV tier block (serve/kv_tier.py) the deployment
        #: pushes; same delta-tracking idiom for its restored counter
        self._kv_tier: Optional[Dict[str, Any]] = None
        self._kv_tier_restored_reported = 0
        #: round-19 healthwatch block (serve/health.py) the deployment
        #: refreshes from its fleet HealthMonitor — zero-shaped when
        #: no monitor watches this engine (standalone / disabled)
        self._health_block: Optional[Dict[str, Any]] = None
        self._spec = {"proposed": 0, "accepted": 0, "rounds": 0}
        #: chunked streaming prefill (round 15): admissions split into
        #: block-sized chunks interleaved with decode waves
        self._chunks = {"requests": 0, "chunks": 0, "tokens": 0,
                        "max_chunks": 0}
        #: round-18 disaggregated serving: block-granular KV handoffs
        #: between prefill and decode replicas.  Kept OUT of `_counts`
        #: (that dict's keys are a stable "requests" schema contract);
        #: handoffs_out books on the prefill side, everything else on
        #: the decode side.
        self._handoff = {"handoffs_out": 0, "handoffs_in": 0,
                         "blocks_moved": 0, "fast_path": 0,
                         "staged": 0, "requeues": 0}
        #: round-12 flight recorder: every lifecycle transition below
        #: also journals a compact decision event (one deque append)
        #: so postmortems can replay what the engine DID, not just its
        #: percentiles.  The SLO watchdog (serve/slo.py) attaches
        #: itself as `slo` when the deployment configures targets.
        self.flightrec = FlightRecorder(deployment)
        self.slo = None

    def _now(self, now: Optional[float]) -> float:
        return time.perf_counter() if now is None else now

    @staticmethod
    def _trace_tag(rec: Dict[str, Any]) -> Dict[str, str]:
        """Flightrec field tagging the event with the request's trace
        id, when one is in scope — lets postmortems follow a single
        request across the journal ({} keeps untraced events lean)."""
        ctx = rec.get("ctx")
        return {"trace": ctx.trace_id} if ctx is not None else {}

    # -- lifecycle ---------------------------------------------------------

    def record_enqueue(self, prompt_len: int,
                       now: Optional[float] = None,
                       tenant: Optional[str] = None,
                       ctx: Optional[TraceContext] = None,
                       engine_now: Optional[float] = None
                       ) -> Dict[str, Any]:
        """`tenant` tags the record for per-tenant SLO slicing (fleet
        router traffic classes); `now` may be BACKDATED to the instant
        the request entered the fleet router, so TTFT/e2e/queue-wait
        series charge router queueing to the request — the fleet-level
        latency a client actually observed, not just engine wait.
        `ctx` is the TraceContext born at router submit (a fresh
        engine-origin one is minted here when absent and the tracebus
        is enabled); `engine_now` is the instant the ENGINE saw the
        request, kept separate from the backdated `now` so the
        critical-path decomposition can split router wait from engine
        queue wait."""
        backdated = now is not None
        now = self._now(now)
        t_eng = self._now(engine_now) if backdated else now
        if ctx is None and _tracebus_enabled():
            ctx = TraceContext(origin="engine")
        rec: Dict[str, Any] = {
            "id": next(self._ids), "prompt_len": int(prompt_len),
            "enqueue": now, "engine_enqueue": t_eng, "admit": None,
            "first_token": None, "finish": None, "slot": None,
            "bucket": None, "tokens": 0,
            "spec_proposed": 0, "spec_accepted": 0,
            "spec_rounds": 0, "spec_rollback_s": 0.0,
            "requeues": 0, "requeue_ts": None, "kv_reserve": None,
            "kv_fetch": None, "prefill_chunks": None,
            "token_ts": [] if ctx is not None else None,
            "status": "queued", "trace": None, "tenant": tenant,
            "ctx": ctx,
        }
        if tracing.is_enabled():
            rec["trace"] = tracing.record_span(
                f"serve {self.deployment}.request", start=now)
        with self._lock:
            self._counts["enqueued"] += 1
            self._queue_depth += 1
        self._m["queue_depth"].set(self._queue_depth, tags=self._tags)
        return rec

    def record_admit(self, rec: Dict[str, Any], slot: int, bucket: int,
                     now: Optional[float] = None) -> None:
        now = self._now(now)
        rec["admit"] = now
        rec["slot"] = int(slot)
        rec["bucket"] = int(bucket)
        rec["status"] = "active"
        with self._lock:
            self._counts["admitted"] += 1
            self._queue_depth = max(0, self._queue_depth - 1)
            self._active[rec["id"]] = rec
            first_seen = bucket not in self._buckets
            self._buckets[bucket] = self._buckets.get(bucket, 0) + 1
        self._m["admitted"].inc(tags=self._tags)
        self._m["queue_depth"].set(self._queue_depth, tags=self._tags)
        self._m["queue_wait"].observe(
            (now - rec["enqueue"]) * 1e3, tags=self._tags)
        self.flightrec.record(
            "admit", ts=now, req=rec["id"], slot=int(slot),
            bucket=int(bucket),
            wait_ms=round((now - rec["enqueue"]) * 1e3, 3),
            **self._trace_tag(rec))
        if first_seen:
            # a never-seen padded prompt shape means one fresh XLA
            # compile of the prefill program for this bucket
            self._m["prefill_compiles"].inc(
                tags=dict(self._tags, bucket=str(int(bucket))))

    def record_program_compile(self, program: str) -> None:
        """One XLA compile of a named engine program (``serve.decode``,
        ``serve.sharded_decode``, ...) observed while this engine is
        live — usually subscribed to the ``device_stats`` program
        registry, so decode-path shape churn shows up next to the
        prefill-bucket counter instead of staying invisible."""
        with self._lock:
            self._program_compiles[program] = \
                self._program_compiles.get(program, 0) + 1
        self._m["program_compiles"].inc(
            tags=dict(self._tags, program=program))
        self.flightrec.record("compile", program=program)

    def record_storm(self, program: str) -> None:
        """One recompile-storm trip from the device_stats registry
        watchdog (``subscribe_storms``): journaled, and queued for the
        SLO tracker's next check so the anomaly auto-dumps a
        postmortem."""
        self.flightrec.record("recompile_storm", program=program)
        if self.slo is not None:
            self.slo.note_storm(program)

    def record_first_token(self, rec: Dict[str, Any],
                           now: Optional[float] = None) -> None:
        now = self._now(now)
        rec["first_token"] = now
        rec["tokens"] = max(1, rec["tokens"])
        if rec.get("token_ts") is not None:
            rec["token_ts"].append(now)
        self._m["ttft"].observe(
            (now - rec["enqueue"]) * 1e3, tags=self._tags)
        self.flightrec.record(
            "first_token", ts=now, req=rec["id"],
            ttft_ms=round((now - rec["enqueue"]) * 1e3, 3),
            **self._trace_tag(rec))

    def record_token(self, rec: Dict[str, Any], n: int = 1,
                     now: Optional[float] = None) -> None:
        """Stamp `n` decode tokens for one request at one instant (a
        spec-verify dispatch emits several tokens in one device round
        trip, so they legitimately share a timestamp).  The trail
        feeds per-request ITL/TPOT and the inter-token leg of the
        critical path; a no-op when the tracebus is disabled."""
        ts = rec.get("token_ts")
        if ts is None:
            return
        now = self._now(now)
        if n == 1:
            ts.append(now)
        else:
            ts.extend([now] * int(n))

    def record_step(self, n_active: int, dur_s: float,
                    now: Optional[float] = None,
                    n_tokens: Optional[int] = None) -> None:
        """One pooled decode step: `n_active` slots sampled in `dur_s`
        seconds of host walltime.  `n_tokens` overrides the tokens
        credited to the step (spec-decode rounds emit up to k+1 per
        slot per dispatch); default one per active slot."""
        now = self._now(now)
        n_tokens = int(n_active) if n_tokens is None else int(n_tokens)
        with self._lock:
            self._steps.append((now, float(dur_s), n_tokens))
            self._n_steps += 1
            self._tokens += n_tokens
            self._max_active = max(self._max_active, int(n_active))
            self._busy_slot_s += n_active * dur_s
            self._step_s += dur_s
            util = (self._busy_slot_s / (self.max_slots * self._step_s)
                    if self.max_slots and self._step_s else 0.0)
        self._m["inter_token"].observe(dur_s * 1e3, tags=self._tags)
        self._m["active_slots"].set(n_active, tags=self._tags)
        self._m["tokens"].inc(n_tokens, tags=self._tags)
        self._m["slot_utilization"].set(round(util, 4), tags=self._tags)
        if dur_s > 0:
            self._m["tokens_per_sec"].set(
                round(n_tokens / dur_s, 1), tags=self._tags)
        self.flightrec.record(
            "step", ts=now, n_active=int(n_active),
            dur_ms=round(dur_s * 1e3, 3), tokens=n_tokens)

    def record_spec(self, rec: Dict[str, Any], proposed: int,
                    accepted: int,
                    dur_s: Optional[float] = None) -> None:
        """One speculative verify round for one request: the draft
        proposed `proposed` tokens, the target accepted `accepted` of
        them (0 <= accepted <= proposed; the +1 correction/bonus token
        every round also emits is counted by record_step, not here).
        Feeds the per-request acceptance-rate percentiles in
        engine_stats()["spec"] and the serve_spec_* counters.  `dur_s`
        is the round's host walltime; the rejected-position share of
        it accumulates as the request's spec_rollback critical-path
        leg (rejected / (k+1) of the dispatch bought nothing)."""
        proposed, accepted = int(proposed), int(accepted)
        rec["spec_proposed"] += proposed
        rec["spec_accepted"] += accepted
        rec["spec_rounds"] = rec.get("spec_rounds", 0) + 1
        if dur_s and proposed > accepted:
            rec["spec_rollback_s"] = (
                rec.get("spec_rollback_s", 0.0)
                + float(dur_s) * (proposed - accepted) / (proposed + 1))
        with self._lock:
            self._spec["proposed"] += proposed
            self._spec["accepted"] += accepted
            self._spec["rounds"] += 1
        self._m["spec_proposed"].inc(proposed, tags=self._tags)
        self._m["spec_accepted"].inc(accepted, tags=self._tags)
        self._m["spec_rounds"].inc(tags=self._tags)
        self.flightrec.record("spec_round", req=rec["id"],
                              proposed=proposed, accepted=accepted,
                              **self._trace_tag(rec))

    def record_requeue(self, rec: Dict[str, Any], need: int = 0,
                       reason: str = "pool_exhausted",
                       now: Optional[float] = None) -> None:
        """Admission bounced the request back to the queue head (KV
        pool or COW exhaustion).  First bounce stamps `requeue_ts` so
        the critical path can charge the exhaustion stall separately
        from ordinary queue wait."""
        now = self._now(now)
        rec["requeues"] = rec.get("requeues", 0) + 1
        if rec.get("requeue_ts") is None:
            rec["requeue_ts"] = now
        if reason.startswith("handoff"):
            # decode-side pool exhaustion bouncing an arriving handoff
            # back to the queue head — surfaced in the handoff block
            with self._lock:
                self._handoff["requeues"] += 1
        self.flightrec.record(
            "requeue", ts=now, req=rec["id"], need=int(need),
            reason=reason, **self._trace_tag(rec))

    def record_kv_reserve(self, rec: Dict[str, Any], start: float,
                          end: float, blocks: int = 0,
                          hit_blocks: int = 0, evicted: int = 0,
                          reprefill_waste_tokens: int = 0) -> None:
        """The BlockPager reservation window for one admission
        (prefix match + allocate + COW), kept on the record so the
        tracebus can render it as its own span inside queue wait.
        `evicted` counts resident prefixes this reservation pushed
        out; `reprefill_waste_tokens` (patched post-prefill via
        `note_kv_waste` — registration happens after the window)
        counts tokens this admission re-filled that were previously
        resident, so a trace can show WHO thrashed the cache."""
        rec["kv_reserve"] = (float(start), float(end), int(blocks),
                             int(hit_blocks), int(evicted),
                             int(reprefill_waste_tokens))

    def note_kv_waste(self, rec: Dict[str, Any], tokens: int) -> None:
        """Patch the re-prefill waste this admission booked onto its
        kv_reserve tuple — known only at `register_prefix` time, after
        the reservation window closed."""
        kv = rec.get("kv_reserve")
        if kv is not None and tokens:
            rec["kv_reserve"] = kv[:5] + (int(tokens),)

    def record_kv_fetch(self, rec: Dict[str, Any], start: float,
                        end: float, blocks: int = 0, tokens: int = 0,
                        bytes: int = 0) -> None:
        """The host-tier restore window of one admission
        (serve/kv_tier.py): `blocks` evicted prefix blocks re-admitted
        via H2D copy over [start, end] instead of being re-prefilled.
        Kept on the record so critical_path() can carve the window
        out of queue wait as the ``kv_fetch_ms`` component and the
        tracebus can render a ``kv.fetch`` span; per-block journal
        events (key/tenant/bytes) come from the pager itself."""
        rec["kv_fetch"] = (float(start), float(end), int(blocks),
                           int(tokens), int(bytes))

    def record_prefill_chunk(self, rec: Dict[str, Any], start: float,
                             end: float, tokens: int, bucket: int,
                             last: bool = False) -> None:
        """One chunk of a chunked (streaming) prefill: `tokens` prompt
        tokens ingested through the paged_prefill program padded to
        `bucket`, dispatched over [start, end] on the perf_counter
        clock.  The windows accumulate on the record — critical_path()
        bills their sum as the prefill leg and the parked remainder of
        admit → first token as prefill_wait — and the final chunk
        (``last=True``) is the one whose sample becomes the first
        token.  One-shot admissions never call this, so their records
        (and the decomposition) are unchanged."""
        chunks = rec.get("prefill_chunks")
        if chunks is None:
            chunks = rec["prefill_chunks"] = []
            with self._lock:
                self._chunks["requests"] += 1
        chunks.append((float(start), float(end), int(tokens),
                       int(bucket)))
        with self._lock:
            self._chunks["chunks"] += 1
            self._chunks["tokens"] += int(tokens)
            self._chunks["max_chunks"] = max(
                self._chunks["max_chunks"], len(chunks))
        self.flightrec.record(
            "prefill_chunk", ts=end, req=rec["id"],
            chunk=len(chunks) - 1, tokens=int(tokens),
            bucket=int(bucket), last=bool(last),
            dur_ms=round((end - start) * 1e3, 3),
            **self._trace_tag(rec))

    # -- disaggregated prefill/decode handoff (round 18) -------------------

    def record_handoff_out(self, rec: Dict[str, Any], blocks: int = 0,
                           nbytes: int = 0, path: str = "fast",
                           now: Optional[float] = None) -> None:
        """Prefill-side retirement of a handed-off request: this
        engine finished the prompt's last chunk, exported the filled
        KV block rows, and the DECODE replica now owns the request's
        lifecycle.  The record leaves the active set but is NOT
        retired into ``_done`` and books none of the request counters
        — the decode-side record (``record_enqueue_handoff``) is the
        authoritative one, and keeping a second first-token-stamped
        record here would double-count TTFT/e2e in fleet pooling."""
        now = self._now(now)
        rec["finish"] = now
        rec["status"] = "handoff"
        with self._lock:
            self._handoff["handoffs_out"] += 1
            if rec["admit"] is None:
                self._queue_depth = max(0, self._queue_depth - 1)
            self._active.pop(rec["id"], None)
        self._m["queue_depth"].set(self._queue_depth, tags=self._tags)
        self.flightrec.record(
            "handoff_out", ts=now, req=rec["id"], blocks=int(blocks),
            bytes=int(nbytes), path=str(path), **self._trace_tag(rec))

    def record_enqueue_handoff(self, meta: Dict[str, Any],
                               now: Optional[float] = None
                               ) -> Dict[str, Any]:
        """Decode-side record for an arriving pre-filled request.  The
        record is pre-populated with the PREFILL replica's timing
        (enqueue/admit/first-token/chunk windows travel with the
        handoff package) so the critical-path decomposition of the
        finished request reads exactly like a monolithic engine's —
        queue wait is the prefill queue, the prefill leg is the chunk
        windows, and the extra export→install cost shows up ONLY as
        the new ``handoff_ms`` component carved from the decode leg."""
        now = self._now(now)
        ctx = meta.get("ctx")
        rec: Dict[str, Any] = {
            "id": next(self._ids),
            "prompt_len": int(meta.get("prompt_len", 0)),
            "enqueue": meta.get("enqueue", now),
            "engine_enqueue": meta.get("engine_enqueue",
                                       meta.get("enqueue", now)),
            "admit": meta.get("admit"),
            "first_token": meta.get("first_token"),
            "finish": None, "slot": None,
            "bucket": meta.get("bucket"), "tokens": 1,
            "spec_proposed": 0, "spec_accepted": 0,
            "spec_rounds": 0, "spec_rollback_s": 0.0,
            "requeues": int(meta.get("requeues", 0)),
            "requeue_ts": meta.get("requeue_ts"),
            "kv_reserve": meta.get("kv_reserve"),
            "kv_fetch": meta.get("kv_fetch"),
            "kv_handoff": None,
            "prefill_chunks": meta.get("prefill_chunks"),
            "token_ts": ([meta["first_token"]]
                         if ctx is not None
                         and meta.get("first_token") is not None
                         else ([] if ctx is not None else None)),
            "status": "queued", "trace": None,
            "tenant": meta.get("tenant"), "ctx": ctx,
        }
        with self._lock:
            self._counts["enqueued"] += 1
            self._handoff["handoffs_in"] += 1
            self._queue_depth += 1
        self._m["queue_depth"].set(self._queue_depth, tags=self._tags)
        self.flightrec.record(
            "handoff_in", ts=now, req=rec["id"],
            prompt_len=rec["prompt_len"], **self._trace_tag(rec))
        return rec

    def record_admit_handoff(self, rec: Dict[str, Any], slot: int,
                             now: Optional[float] = None) -> None:
        """Admit an arriving handoff into a decode slot.  Unlike
        ``record_admit`` this must NOT overwrite ``admit`` (the
        prefill replica's admission instant is the one the
        decomposition needs) and must not observe queue-wait or
        prefill-bucket metrics — the prefill side already did."""
        now = self._now(now)
        rec["slot"] = int(slot)
        rec["status"] = "active"
        with self._lock:
            self._counts["admitted"] += 1
            self._queue_depth = max(0, self._queue_depth - 1)
            self._active[rec["id"]] = rec
        self._m["admitted"].inc(tags=self._tags)
        self._m["queue_depth"].set(self._queue_depth, tags=self._tags)
        self.flightrec.record(
            "handoff_admit", ts=now, req=rec["id"], slot=int(slot),
            **self._trace_tag(rec))

    def record_kv_handoff(self, rec: Dict[str, Any], start: float,
                          end: float, blocks: int = 0, nbytes: int = 0,
                          path: str = "fast") -> None:
        """The export→install window of one handoff: `blocks` filled
        KV block rows moved from the prefill replica's pool into this
        decode replica's over [start, end] (`path` is "fast" for the
        same-process device copy, "staged" for the D2H→H2D hop through
        host staging buffers).  Kept on the record so critical_path()
        can carve the window out of the decode leg as ``handoff_ms``
        and the tracebus can render a ``kv.handoff`` span."""
        rec["kv_handoff"] = (float(start), float(end), int(blocks),
                             int(nbytes), str(path))
        with self._lock:
            self._handoff["blocks_moved"] += int(blocks)
            if path == "fast":
                self._handoff["fast_path"] += 1
            else:
                self._handoff["staged"] += 1
        self.flightrec.record(
            "kv_handoff", ts=end, req=rec["id"], blocks=int(blocks),
            bytes=int(nbytes), path=str(path),
            dur_ms=round((end - start) * 1e3, 3),
            **self._trace_tag(rec))

    def record_finish(self, rec: Dict[str, Any],
                      n_tokens: Optional[int] = None,
                      now: Optional[float] = None) -> None:
        now = self._now(now)
        rec["finish"] = now
        if n_tokens is not None:
            rec["tokens"] = int(n_tokens)
        rec["status"] = "ok"
        self._retire(rec, "finished")
        self._m["finished"].inc(tags=self._tags)
        self._m["latency"].observe(
            (now - rec["enqueue"]) * 1e3, tags=self._tags)
        self.flightrec.record(
            "finish", ts=now, req=rec["id"], slot=rec["slot"],
            tokens=rec["tokens"],
            latency_ms=round((now - rec["enqueue"]) * 1e3, 3),
            **self._trace_tag(rec))
        if rec["trace"] is not None:
            trace_id, span_id = rec["trace"]
            start = (rec["admit"] if rec["admit"] is not None
                     else rec["enqueue"])
            tracing.record_span(f"engine {self.deployment}.generate",
                                trace_id=trace_id, parent_id=span_id,
                                start=start,
                                duration=max(0.0, now - start))

    def record_reject(self, rec: Dict[str, Any], reason: str = "",
                      now: Optional[float] = None,
                      label: str = "invalid") -> None:
        """`reason` is the free-form human string kept on the request
        record; `label` is the LOW-CARDINALITY metric tag ("oversized",
        "shed_queue_full", ...) — never put request-specific text in a
        metric label."""
        rec["finish"] = self._now(now)
        rec["status"] = "rejected"
        rec["reason"] = reason
        with self._lock:
            self._rejections_by_reason[label] = \
                self._rejections_by_reason.get(label, 0) + 1
        self._retire(rec, "rejected")
        self._m["rejected"].inc(tags=dict(self._tags, reason=label))
        self.flightrec.record(
            "shed" if label.startswith("shed") else "reject",
            req=rec["id"], label=label, reason=reason[:120],
            **self._trace_tag(rec))

    # -- paged KV cache (serve/kv_pager.py feeds these) --------------------

    def record_prefix_reuse(self, hit_blocks: int,
                            miss_blocks: int) -> None:
        """One admission's prefix-cache outcome, in blocks."""
        if hit_blocks:
            self._m["prefix_hits"].inc(int(hit_blocks), tags=self._tags)
        if miss_blocks:
            self._m["prefix_misses"].inc(int(miss_blocks),
                                         tags=self._tags)

    def record_cow(self) -> None:
        self._m["cow_copies"].inc(tags=self._tags)
        self.flightrec.record("cow_fork")

    def record_kv_stats(self, stats: Dict[str, Any]) -> None:
        """Latest BlockPager.stats() snapshot — mirrored into
        engine_stats()["kv_cache"] and the blocks-in-use gauge."""
        with self._lock:
            self._kv_stats = dict(stats)
        self._m["kv_blocks_in_use"].set(
            int(stats.get("blocks_in_use", 0)), tags=self._tags)

    def record_kv_scope(self, block: Dict[str, Any]) -> None:
        """Latest composed kvscope block (occupancy + forensics + HBM
        ledger, see serve/kvscope.py) — mirrored into
        engine_stats()["kv_scope"] and the kvscope gauges; the waste
        Prometheus counter advances by the delta since the last push
        (stats carry totals, counters take increments)."""
        occ = block.get("occupancy") or {}
        forensics = block.get("forensics") or {}
        with self._lock:
            self._kv_scope = block
            waste = int(forensics.get("reprefill_waste_tokens", 0))
            delta = waste - self._kv_waste_reported
            if delta > 0:
                self._kv_waste_reported = waste
        self._m["kv_occupancy"].set(
            float(occ.get("occupancy_ratio", 0.0)), tags=self._tags)
        self._m["kv_fragmentation"].set(
            float(occ.get("fragmentation", 0.0)), tags=self._tags)
        if delta > 0:
            self._m["kv_reprefill_waste"].inc(delta, tags=self._tags)

    def record_kv_tier(self, block: Dict[str, Any]) -> None:
        """Latest HostKVTier.stats() block (serve/kv_tier.py) —
        mirrored into engine_stats()["kv_tier"] and the tier gauges;
        the tokens-restored Prometheus counter advances by the delta
        since the last push (stats carry totals, counters take
        increments)."""
        with self._lock:
            self._kv_tier = dict(block)
            restored = int(block.get("tokens_restored", 0))
            delta = restored - self._kv_tier_restored_reported
            if delta > 0:
                self._kv_tier_restored_reported = restored
        self._m["kv_tier_bytes"].set(
            int(block.get("bytes_resident", 0)), tags=self._tags)
        self._m["kv_tier_hit_rate"].set(
            float(block.get("hit_rate", 0.0)), tags=self._tags)
        if delta > 0:
            self._m["kv_tier_restored"].inc(delta, tags=self._tags)

    def record_health(self, block: Dict[str, Any]) -> None:
        """Latest healthwatch block (serve/health.py
        ``HealthMonitor.replica_block``) — mirrored into
        ``engine_stats()["health"]``.  The monitor publishes its own
        Prometheus gauges/counters at transition time; this is the
        stats-surface mirror only."""
        with self._lock:
            self._health_block = dict(block)

    def stalled_requests(self, stall_ms: float,
                         now: Optional[float] = None
                         ) -> List[Dict[str, Any]]:
        """Admitted-but-token-silent requests: active records whose
        last emitted token (or admission, when no token yet) is older
        than ``stall_ms`` — the healthwatch stall sweep's feed.  Each
        entry carries the flightrec-known resident state (slot,
        tokens emitted, tenant, trace) so the ``request_stall``
        journal entry names exactly what is wedged."""
        now = self._now(now)
        with self._lock:
            recs = list(self._active.values())
        out: List[Dict[str, Any]] = []
        for r in recs:
            if r.get("status") != "active":
                continue
            ts = r.get("token_ts")
            last = ts[-1] if ts else (r.get("first_token")
                                      or r.get("admit"))
            if last is None:
                continue
            silent_ms = (now - last) * 1e3
            if silent_ms < stall_ms:
                continue
            ctx = r.get("ctx")
            out.append({
                "id": r["id"],
                "slot": r.get("slot"),
                "tokens": int(r.get("tokens", 0)),
                "tenant": r.get("tenant"),
                "silent_ms": round(silent_ms, 3),
                "trace": ctx.trace_id if ctx is not None else None,
            })
        return out

    # -- fleet control plane (serve/router.py journals through here) -------

    def record_route(self, req: int, replica: str, policy: str,
                     tenant: Optional[str] = None,
                     matched_blocks: int = 0,
                     outstanding: int = 0,
                     now: Optional[float] = None,
                     trace: Optional[str] = None) -> None:
        """One routing decision: request `req` dispatched to `replica`
        under `policy` ("prefix_affinity" | "p2c" | "round_robin"),
        having matched `matched_blocks` resident prefix blocks there.
        `outstanding` is the replica's in-flight count at dispatch —
        the load the power-of-two-choices fallback compared.  `trace`
        is the request's tracebus id when one is in scope."""
        self.flightrec.record(
            "route", ts=now, req=int(req), replica=str(replica),
            policy=str(policy), tenant=tenant,
            matched_blocks=int(matched_blocks),
            outstanding=int(outstanding),
            **({"trace": trace} if trace is not None else {}))

    def record_scale(self, direction: str, n_before: int, n_after: int,
                     reason: str, signal: float = 0.0,
                     replica: Optional[str] = None,
                     now: Optional[float] = None) -> None:
        """One autoscaling decision.  `direction` is "up" or "down"
        (journaled as the `scale_up` / `scale_down` event kinds),
        `reason` names the tripped signal ("burn_rate" | "queue_depth"
        | "idle"), `signal` its value at the decision."""
        kind = "scale_up" if direction == "up" else "scale_down"
        self.flightrec.record(
            kind, ts=now, n_before=int(n_before), n_after=int(n_after),
            reason=str(reason), signal=round(float(signal), 4),
            replica=replica)

    def record_drain(self, replica: str, ok: bool,
                     blocks_in_use: int = 0, drained_requests: int = 0,
                     now: Optional[float] = None) -> None:
        """Graceful-drain outcome for one replica: admission was
        stopped, `drained_requests` in-flight requests finished, and
        `blocks_in_use` KV blocks remained after retirement (0 on a
        clean drain)."""
        self.flightrec.record(
            "drain", ts=now, replica=str(replica), ok=bool(ok),
            blocks_in_use=int(blocks_in_use),
            drained_requests=int(drained_requests))

    def record_error(self, rec: Dict[str, Any], error: str = "",
                     now: Optional[float] = None) -> None:
        rec["finish"] = self._now(now)
        rec["status"] = "error"
        rec["reason"] = error
        self._retire(rec, "errors")
        self._m["errors"].inc(tags=self._tags)
        self.flightrec.record("error", req=rec["id"],
                              error=error[:200],
                              **self._trace_tag(rec))

    def _retire(self, rec: Dict[str, Any], count_key: str) -> None:
        with self._lock:
            self._counts[count_key] += 1
            if rec["admit"] is None:
                self._queue_depth = max(0, self._queue_depth - 1)
            self._active.pop(rec["id"], None)
            self._done.append(rec)
        self._m["queue_depth"].set(self._queue_depth, tags=self._tags)

    # -- sinks -------------------------------------------------------------

    def slo_samples(self, tenant: Optional[str] = None
                    ) -> Dict[str, List[tuple]]:
        """(event_ts, value_ms) series per SLO objective over the
        retained records — the raw stream serve/slo.py's burn-rate
        windows slice.  Timestamps are the perf_counter instant each
        value became OBSERVABLE (first token, admit, finish), so a
        window query sees exactly what a live observer saw.  With
        `tenant` the series are restricted to that traffic class's
        records (fleet per-tenant attainment); default is all."""
        with self._lock:
            recs = list(self._done) + list(self._active.values())
        if tenant is not None:
            recs = [r for r in recs if r.get("tenant") == tenant]
        out: Dict[str, List[tuple]] = {"ttft": [], "e2e": [],
                                       "queue_wait": []}
        for r in recs:
            if r.get("status") == "handoff":
                # prefill-side shadow of a handed-off request: the
                # decode replica's record is the authoritative one
                continue
            if r["first_token"] is not None:
                out["ttft"].append(
                    (r["first_token"],
                     (r["first_token"] - r["enqueue"]) * 1e3))
            if r["admit"] is not None:
                out["queue_wait"].append(
                    (r["admit"], (r["admit"] - r["enqueue"]) * 1e3))
            if r["finish"] is not None and r["status"] == "ok":
                out["e2e"].append(
                    (r["finish"], (r["finish"] - r["enqueue"]) * 1e3))
        return out

    def anatomy_samples(self, tenant: Optional[str] = None
                        ) -> Dict[str, Any]:
        """Raw latency-anatomy samples over retired records: pooled
        inter-token gaps, per-request TPOT, and the critical-path
        decomposition per component — the un-summarized stream that
        fleet_stats pools across replicas before taking percentiles."""
        with self._lock:
            recs = list(self._done)
        if tenant is not None:
            recs = [r for r in recs if r.get("tenant") == tenant]
        out = empty_anatomy_samples()
        tenants: set = set()
        for r in recs:
            if r.get("status") == "handoff":
                continue
            if r.get("tenant"):
                tenants.add(r["tenant"])
            out["itl_ms"].extend(_token_gaps_ms(r))
            if r.get("first_token") is not None:
                out["ttft_ms"].append(
                    (r["first_token"] - r["enqueue"]) * 1e3)
            cp = critical_path(r)
            if cp is not None:
                for k, v in cp.items():
                    out["critical_path"][k].append(v)
            if (r.get("status") == "ok" and r.get("finish") is not None
                    and r.get("first_token") is not None
                    and r.get("tokens", 0) > 1):
                out["tpot_ms"].append(
                    (r["finish"] - r["first_token"]) * 1e3
                    / (r["tokens"] - 1))
        out["tenants"] = sorted(tenants)
        return out

    def trace_records(self) -> List[Dict[str, Any]]:
        """Tracebus view of every retained request (retired + live) as
        plain dicts — what the fleet collector merges."""
        with self._lock:
            recs = list(self._done) + list(self._active.values())
        return [request_snapshot(r, self.deployment) for r in recs]

    def find_request(self, request_id: Any) -> Optional[Dict[str, Any]]:
        """Locate one request by trace id (full or unambiguous prefix)
        or by engine-local integer id; None when unknown here."""
        rid = str(request_id)
        with self._lock:
            recs = list(self._done) + list(self._active.values())
        for r in recs:
            ctx = r.get("ctx")
            if ctx is not None and (ctx.trace_id == rid
                                    or (len(rid) >= 6
                                        and ctx.trace_id.startswith(rid))):
                return request_snapshot(r, self.deployment)
            if str(r["id"]) == rid:
                return request_snapshot(r, self.deployment)
        return None

    def engine_stats(self) -> Dict[str, Any]:
        """Snapshot of everything ``bench``/dashboards ask the engine:
        percentiles over retained records, counters, throughput, and
        slot occupancy — cheap enough to call per scrape."""
        with self._lock:
            recs = list(self._done) + list(self._active.values())
            n_active = len(self._active)
            steps = list(self._steps)
            counts = dict(self._counts)
            queue_depth = self._queue_depth
            max_active = self._max_active
            n_steps = self._n_steps
            tokens = self._tokens
            busy, step_s = self._busy_slot_s, self._step_s
            buckets = dict(self._buckets)
            program_compiles = dict(self._program_compiles)
            rejections = dict(self._rejections_by_reason)
            kv_stats = (dict(self._kv_stats)
                        if self._kv_stats is not None else None)
            kv_scope = self._kv_scope
            kv_tier = self._kv_tier
            health = self._health_block
            spec = dict(self._spec)
            chunks = dict(self._chunks)
            handoff = dict(self._handoff)
        recs = [r for r in recs if r.get("status") != "handoff"]
        ttft = [(r["first_token"] - r["enqueue"]) * 1e3 for r in recs
                if r["first_token"] is not None]
        qwait = [(r["admit"] - r["enqueue"]) * 1e3 for r in recs
                 if r["admit"] is not None]
        lat = [(r["finish"] - r["enqueue"]) * 1e3 for r in recs
               if r["finish"] is not None and r["status"] == "ok"]
        inter = [d * 1e3 for _, d, _ in steps]
        anatomy = self.anatomy_samples()
        by_tenant = {t: latency_anatomy(self.anatomy_samples(tenant=t))
                     for t in anatomy["tenants"]}
        if steps:
            window = (steps[-1][0] - steps[0][0] + steps[0][1])
            win_tokens = sum(n for _, _, n in steps)
            throughput = win_tokens / window if window > 0 else 0.0
        else:
            throughput = 0.0
        return {
            "deployment": self.deployment,
            # round-18: disaggregated serving role — "prefill" engines
            # park at handoff, "decode" engines admit pre-filled
            # requests, "both" is the monolithic engine
            "role": self.role,
            "uptime_s": round(time.perf_counter() - self._t0, 3),
            "requests": dict(counts, active=n_active,
                             queued=queue_depth),
            "ttft_ms": _core.summarize(ttft),
            "queue_wait_ms": _core.summarize(qwait),
            "request_latency_ms": _core.summarize(lat),
            "inter_token_ms": _core.summarize(inter),
            "engine_steps": n_steps,
            "tokens_generated": tokens,
            "tokens_per_sec": round(throughput, 1),
            "slot_utilization": round(
                busy / (self.max_slots * step_s), 4)
                if self.max_slots and step_s else 0.0,
            "max_active_slots": max_active,
            "max_slots": self.max_slots,
            "prefill_buckets": {str(k): v
                                for k, v in sorted(buckets.items())},
            "prefill_compiles": len(buckets),
            # round-10: XLA compiles keyed by engine program name
            # (device_stats registry subscription) — decode-path
            # recompile churn, not just prefill buckets
            "program_compiles": {k: v for k, v
                                 in sorted(program_compiles.items())},
            # round-8: paged-KV + admission-control surfaces (top-level
            # keys — the "requests" dict shape is a stable contract)
            "rejections_by_reason": rejections,
            "kv_cache": kv_stats,
            # round-16: kvscope — occupancy ring + eviction forensics
            # + unified HBM ledger (stable empty-shaped block on
            # dense engines, which have no pager to observe)
            "kv_scope": (kv_scope if kv_scope is not None
                         else _empty_kv_scope()),
            # round-17: tiered host-RAM KV cache — spill/restore
            # counters + engine-fed H2D/D2H cost (stable zero-shaped
            # block when no tier is configured, dense included)
            "kv_tier": (kv_tier if kv_tier is not None
                        else _empty_kv_tier()),
            # round-19: healthwatch — liveness state machine counters
            # (stable zero-shaped block when no HealthMonitor watches
            # this engine: standalone, dense, or RAYTPU_HEALTHWATCH=0)
            "health": (health if health is not None
                       else _empty_health()),
            # round-11: speculative decoding — engine totals plus
            # per-request acceptance-rate percentiles (requests that
            # saw at least one verify round)
            "spec": {
                "proposed": spec["proposed"],
                "accepted": spec["accepted"],
                "rejected": spec["proposed"] - spec["accepted"],
                "rounds": spec["rounds"],
                "accept_rate": round(
                    spec["accepted"] / spec["proposed"], 4)
                    if spec["proposed"] else None,
                "accept_rate_per_request": _core.summarize(
                    [r["spec_accepted"] / r["spec_proposed"]
                     for r in recs if r.get("spec_proposed", 0)]),
            },
            # round-15: chunked streaming prefill — long prompts
            # admitted as block-sized chunks interleaved with decode
            # waves (all zeros when prefill_chunk_tokens is unset)
            "prefill_chunks": {
                "requests": chunks["requests"],
                "chunks": chunks["chunks"],
                "tokens": chunks["tokens"],
                "max_chunks_per_request": chunks["max_chunks"],
            },
            # round-18: disaggregated prefill/decode handoffs — block
            # moves out of (prefill role) and into (decode role) this
            # engine's pool, by path, plus decode-side pool-exhaustion
            # requeues (all zeros on monolithic engines)
            "handoff": handoff,
            # round-14: per-token latency anatomy — ITL/TPOT
            # percentiles and the critical-path decomposition
            # (e2e = router_wait + queue_wait + requeue + prefill +
            # inter_token + spec_rollback), overall and per tenant
            "latency_anatomy": dict(latency_anatomy(anatomy),
                                    by_tenant=by_tenant),
            # round-12: SLO burn rates (None until the deployment
            # configures an SLOConfig — key presence is the contract)
            # and the flight recorder's ring occupancy/drop counters
            "slo": (self.slo.snapshot() if self.slo is not None
                    else None),
            "flightrec": self.flightrec.stats(),
            # round-13: the roofline constants of THIS engine's device,
            # so a dashboard attributing a remote engine's programs
            # classifies against the remote ridge, not the reader's
            "device": _device_roofline(),
        }

    def export_timeline(self, filename: Optional[str] = None
                        ) -> List[Dict[str, Any]]:
        """Chrome-trace events in the ``ray_tpu.timeline()`` shape:
        lane 0 is the admission queue, lanes 1..max_slots are per-slot
        occupancy (prefill + decode span per request), and the last
        lane carries the pooled engine steps.  Timestamps are relative
        to engine start (chrome-trace origins are arbitrary)."""
        with self._lock:
            recs = list(self._done) + list(self._active.values())
            steps = list(self._steps)
        pid = 1
        base = self._t0
        step_lane = self.max_slots + 1
        events: List[Dict[str, Any]] = [
            _core.process_name_event(
                pid, f"llm-engine {self.deployment}"),
            _core.thread_name_event(pid, 0, "queue"),
            _core.thread_name_event(pid, step_lane, "engine steps"),
        ]
        for slot in range(self.max_slots):
            events.append(
                _core.thread_name_event(pid, slot + 1, f"slot {slot}"))
        now = time.perf_counter()
        for r in recs:
            end = r["finish"] if r["finish"] is not None else now
            admit = r["admit"] if r["admit"] is not None else end
            events.append(_core.complete_event(
                f"queued req{r['id']}", "serve", r["enqueue"] - base,
                admit - r["enqueue"], pid, 0,
                {"request_id": r["id"], "status": r["status"],
                 "prompt_len": r["prompt_len"]}))
            if r["admit"] is None:
                continue
            lane = (r["slot"] + 1) if r["slot"] is not None else 0
            first = (r["first_token"] if r["first_token"] is not None
                     else min(admit, end))
            events.append(_core.complete_event(
                f"prefill req{r['id']}", "serve", admit - base,
                first - admit, pid, lane,
                {"request_id": r["id"], "bucket": r["bucket"],
                 "prompt_len": r["prompt_len"]}))
            events.append(_core.complete_event(
                f"decode req{r['id']}", "serve", first - base,
                end - first, pid, lane,
                {"request_id": r["id"], "tokens": r["tokens"],
                 "status": r["status"]}))
        for end_ts, dur, n_active in steps:
            events.append(_core.complete_event(
                "engine_step", "serve", end_ts - dur - base, dur, pid,
                step_lane, {"active_slots": n_active}))
        return _core.write_chrome_trace(events, filename)
