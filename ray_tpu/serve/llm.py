"""LM serving: the model zoo's KV-cache decoders behind a serve
deployment.

No reference analog module (the reference serves user torch models);
this packages the composition its users hand-roll — model init or
checkpoint load, jitted prefill/decode programs, request batching —
so `serve.run(build_llm_deployment(...).bind())` is a working LM
endpoint for either decoder family (gpt2 / llama).

Two schedulers:

  * "batch" — @serve.batch micro-batching: concurrent requests are
    collected into one `generate` call and run TO COMPLETION together.
    Ragged prompt lists are LEFT-padded before stacking (the decode
    cache contract) and the pads trimmed from each returned row;
    equal-length batches keep the pad-free fast path (flash-eligible
    prefill).
  * "continuous" — slot-based continuous batching: a fixed pool of
    `max_slots` KV-cache rows.  Each admitted request gets ONE batched
    prefill dispatch into a free slot; all active slots then share one
    jitted decode step per token.  Finished sequences free their slot
    immediately and queued requests are admitted mid-flight — short
    requests are never held hostage by long ones, the failure mode of
    stack-and-pray fixed batching.  Prompt lengths are padded up to
    `prefill_bucket` multiples so the prefill program compiles once
    per bucket, not once per length.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.serve.api import deployment
from ray_tpu.serve.batching import OverloadedError, RequestQueue
from ray_tpu.serve.batching import batch as _batch
from ray_tpu.serve.telemetry import EngineTelemetry


def _family_fns(family: str):
    """(config_fn, init_fn, generate_fn, prefill_fn, step_fn,
    init_cache_fn, init_paged_cache_fn, paged_prefill_fn,
    logical_axes_fn) for a decoder family."""
    if family == "gpt2":
        from ray_tpu.models import (gpt2_config, gpt2_init,
                                    gpt2_logical_axes)
        from ray_tpu.models.gpt2_decode import (decode_step, generate,
                                                init_cache,
                                                init_paged_cache,
                                                paged_prefill, prefill)

        return (gpt2_config, gpt2_init, generate, prefill, decode_step,
                init_cache, init_paged_cache, paged_prefill,
                gpt2_logical_axes)
    from ray_tpu.models import (llama_config, llama_init,
                                llama_logical_axes)
    from ray_tpu.models.llama_decode import (llama_decode_step,
                                             llama_generate,
                                             llama_init_cache,
                                             llama_init_paged_cache,
                                             llama_paged_prefill,
                                             llama_prefill)

    return (llama_config, llama_init, llama_generate, llama_prefill,
            llama_decode_step, llama_init_cache,
            llama_init_paged_cache, llama_paged_prefill,
            llama_logical_axes)


# jax's compile cache is keyed by the jitted function OBJECT, so a
# fresh `jax.jit(closure)` per engine instance recompiles every
# program for every instance — pathological for test suites and
# notebooks that build many short-lived engines.  The continuous
# engine's programs depend only on (family fns, config, temperature,
# kv layout, mesh); configs are frozen dataclasses and jax Meshes are
# hashable by (axis names, device assignment), so equal-config engines
# can share ONE set of jitted callables and therefore one compile —
# while engines that differ only in layout or mesh get their own
# entries instead of colliding.
_JIT_CACHE: Dict[Any, Any] = {}


def _jitted_engine_fns(prefill_fn, step_fn, paged_prefill_fn, cfg,
                       temperature, kv_layout="dense", mesh=None):
    """(prefill, paged_prefill, pool_step, admit, copy_block,
    clear_row) jitted programs for one (family, cfg, temperature,
    kv_layout, mesh) engine identity."""
    key = (prefill_fn, step_fn, paged_prefill_fn, cfg, temperature,
           kv_layout, mesh)
    cached = _JIT_CACHE.get(key)
    if cached is not None:
        return cached
    import jax
    from jax import lax

    from ray_tpu.models.decode_common import (copy_block,
                                              make_vocab_tail_mask,
                                              sample_token)

    tail = make_vocab_tail_mask(cfg)

    def prefill_sample(p, toks, lens, k):
        logits, cache = prefill_fn(p, toks, cfg, lengths=lens)
        return sample_token(logits, k, temperature, tail), cache

    def paged_prefill_sample(p, cache, toks, row_bt, prefix_len,
                             n_tail, slot, k):
        logits, cache = paged_prefill_fn(
            p, cache, toks, cfg, row_bt=row_bt,
            prefix_len=prefix_len, n_tail=n_tail, slot=slot)
        return sample_token(logits[None], k, temperature, tail), cache

    def pool_step(p, cache, toks, k):
        logits, cache = step_fn(p, cache, toks, cfg)
        return sample_token(logits, k, temperature, tail), cache

    def admit(pool, row, slot):
        out = dict(pool)
        for name in ("k", "v"):   # (L, B, S, ...): row b=slot
            out[name] = lax.dynamic_update_slice_in_dim(
                pool[name], row[name], slot, axis=1)
        for name in ("pos", "start"):
            out[name] = lax.dynamic_update_slice_in_dim(
                pool[name], row[name], slot, axis=0)
        return out

    def clear_row(cache, slot):
        # retire a row: its table points at the null block so the
        # (masked, unread) writes of an idle row can never land in a
        # block the pager has handed to someone else
        out = dict(cache)
        out["block_tables"] = cache["block_tables"].at[slot].set(0)
        out["pos"] = cache["pos"].at[slot].set(0)
        return out

    # perf observatory: the three heavy programs report compiles /
    # compiler cost model / invoke walltimes to the process-wide
    # registry under stable names (sharded engines get their own so
    # single- and multi-chip cost models never mix)
    from ray_tpu._private.device_stats import get_registry

    registry = get_registry()
    shard = "serve.sharded_" if mesh is not None else "serve."
    n_dev = len(getattr(mesh, "devices", [[None]]).flat) \
        if mesh is not None else 1
    fns = (registry.instrument(shard + "prefill",
                               jax.jit(prefill_sample), n_dev),
           registry.instrument(shard + "paged_prefill",
                               jax.jit(paged_prefill_sample), n_dev),
           registry.instrument(shard + "decode",
                               jax.jit(pool_step), n_dev),
           jax.jit(admit), jax.jit(copy_block),
           jax.jit(clear_row))
    _JIT_CACHE[key] = fns
    return fns


def build_llm_deployment(family: str = "gpt2", preset: str = "nano",
                         *, max_new_tokens: int = 16,
                         temperature: float = 0.0,
                         max_batch_size: int = 8,
                         batch_wait_timeout_s: float = 0.05,
                         checkpoint_path: Optional[str] = None,
                         seed: int = 0, num_replicas: int = 1,
                         scheduler: str = "batch",
                         max_slots: int = 4,
                         prefill_bucket: int = 16,
                         kv_layout: str = "dense",
                         kv_block_size: int = 16,
                         kv_num_blocks: Optional[int] = None,
                         admission_policy=None,
                         mesh=None,
                         config_overrides: Optional[Dict[str, Any]]
                         = None):
    """A serve Deployment generating continuations for int32
    token-prompt arrays (1-D per request; ragged lengths welcome —
    each caller gets back its own prompt + continuation, pads
    trimmed).

    family: "gpt2" | "llama"; preset: a model-zoo preset name.
    scheduler: "batch" (@serve.batch fixed micro-batches) or
    "continuous" (slot pool of `max_slots` KV rows with mid-flight
    admission; `prefill_bucket` bounds prefill recompiles).
    kv_layout: "dense" (per-slot rows, the parity oracle) or "paged"
    (shared block pool + per-row block tables managed by
    serve/kv_pager.py — prompt prefixes resident from earlier requests
    are reused instead of re-prefilled, with copy-on-write forks at
    shared write boundaries).  kv_block_size sets the block token
    granularity; kv_num_blocks the pool size (default: enough for
    every slot plus one sequence of prefix-cache headroom).
    admission_policy: a serve.batching.AdmissionPolicy closing the
    telemetry loop — requests are load-shed with OverloadedError when
    its queue-depth / queue-wait / TTFT gates trip.
    mesh: a `jax.sharding.Mesh` to tensor-parallelise the engine over
    (continuous scheduler only).  Params and the KV pool are committed
    to the mesh under parallel.sharding.DECODE_RULES — attention
    heads, MLP hidden, lm-head vocab, and the pool's KV-head dim split
    over the `tensor` axis (dims the degree doesn't divide replicate);
    the committed input shardings propagate through the existing
    jitted programs, so one pool step spans all chips.  Block tables
    and the BlockPager stay host-side and layout-agnostic.  None (the
    default) keeps today's single-device behaviour.
    checkpoint_path: pickled param pytree (matching the family's init
    layout); absent → fresh init from `seed` (tests/demos)."""
    if family not in ("gpt2", "llama"):
        raise ValueError(f"unknown LM family {family!r}")
    if scheduler not in ("batch", "continuous"):
        raise ValueError(f"unknown scheduler {scheduler!r} "
                         f"(expected 'batch' or 'continuous')")
    if kv_layout not in ("dense", "paged"):
        raise ValueError(f"unknown kv_layout {kv_layout!r} "
                         f"(expected 'dense' or 'paged')")
    if kv_layout == "paged" and scheduler != "continuous":
        raise ValueError("kv_layout='paged' requires "
                         "scheduler='continuous' (the block pager "
                         "lives in the continuous engine)")
    if mesh is not None and scheduler != "continuous":
        raise ValueError("mesh-sharded serving requires "
                         "scheduler='continuous' (the batch scheduler "
                         "is single-device)")

    class LLM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            overrides = dict(config_overrides or {})
            (config_fn, init_fn, gen_fn, prefill_fn, step_fn,
             init_cache_fn, init_paged_fn, paged_prefill_fn,
             logical_axes_fn) = _family_fns(family)
            self.cfg = config_fn(preset, **overrides)
            if checkpoint_path:
                with open(checkpoint_path, "rb") as f:
                    self.params = jax.tree.map(jnp.asarray,
                                               pickle.load(f))
            else:
                self.params = init_fn(jax.random.PRNGKey(seed),
                                      self.cfg)
            self.mesh = mesh
            if mesh is not None:
                # commit params to the mesh once at construction; the
                # committed shardings propagate through every jitted
                # program below, turning them SPMD without annotation
                from ray_tpu.parallel.sharding import (DECODE_RULES,
                                                       shard_by_shape)
                self.params = shard_by_shape(
                    self.params, logical_axes_fn(self.cfg), mesh,
                    DECODE_RULES)
            # per-call PRNG threading: without it every temperature>0
            # request would sample under the same default key and
            # return identical "random" continuations
            self._rng = jax.random.PRNGKey(seed + 1)
            # host-side lifecycle telemetry (enqueue/admit/first-token/
            # step/finish records -> metrics + engine_stats + timeline);
            # never touches the jitted programs
            self._telemetry = EngineTelemetry(
                f"llm_{family}_{preset}",
                max_slots=(max_slots if scheduler == "continuous"
                           else max_batch_size))
            if scheduler == "batch":
                self._generate = jax.jit(
                    lambda p, toks, k: gen_fn(
                        p, toks, self.cfg,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, key=k))
                self._generate_ragged = jax.jit(
                    lambda p, toks, lens, k: gen_fn(
                        p, toks, self.cfg, lengths=lens,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, key=k))
            else:
                self._init_continuous(prefill_fn, step_fn,
                                      init_cache_fn, init_paged_fn,
                                      paged_prefill_fn)

        # ------------------------------------------------------------
        # "batch" scheduler: @serve.batch over (possibly ragged) lists
        # ------------------------------------------------------------

        @_batch(max_batch_size=max_batch_size,
                batch_wait_timeout_s=batch_wait_timeout_s)
        async def _call_batch(self, prompts):
            import jax
            import jax.numpy as jnp

            self._rng, k = jax.random.split(self._rng)
            # host-side prompt normalization (python ints, no device
            # fetch) # graftcheck: disable=blocking-call-in-async
            arrs = [np.asarray(p, np.int32).reshape(-1)
                    for p in prompts]
            lens = [int(a.shape[0]) for a in arrs]
            t0 = max(lens)
            if min(lens) == t0:
                # equal-length fast path: no pads, flash-eligible
                toks = jnp.asarray(np.stack(arrs), jnp.int32)
                out = self._generate(self.params, toks, k)
                # deliberate result fetch: the batch is done on device
                # and callers need host arrays
                # graftcheck: disable=blocking-call-in-async
                return [np.asarray(row) for row in out]
            padded = np.zeros((len(arrs), t0), np.int32)
            for i, a in enumerate(arrs):
                padded[i, t0 - lens[i]:] = a
            out = self._generate_ragged(
                self.params, jnp.asarray(padded),
                jnp.asarray(lens, jnp.int32), k)
            # trim the left pads: each caller sees prompt+continuation
            # (deliberate result fetch, same as the fast path above)
            # graftcheck: disable=blocking-call-in-async
            return [np.asarray(row)[t0 - n:]
                    for row, n in zip(out, lens)]

        async def _call_batch_traced(self, prompt):
            # request-level telemetry wraps the @serve.batch queue so
            # the recorded latency includes the batch-collection wait
            # prompt is a host-side list; measuring its length moves
            # no device data
            # graftcheck: disable=blocking-call-in-async
            n_prompt = int(np.asarray(prompt).reshape(-1).shape[0])
            rec = self._telemetry.record_enqueue(n_prompt)
            if n_prompt == 0 or \
                    n_prompt + max_new_tokens > self.cfg.max_seq:
                # pre-validate BEFORE batching: an oversized prompt
                # used to blow up the whole micro-batch from inside
                # generate (and bypassed the rejection metrics lane)
                self._telemetry.record_reject(
                    rec, reason=f"prompt length {n_prompt}",
                    label="oversized")
                raise ValueError(
                    f"prompt length {n_prompt} invalid for "
                    f"max_seq={self.cfg.max_seq} with "
                    f"max_new_tokens={max_new_tokens}")
            try:
                out = await self._call_batch(prompt)
            except Exception as e:  # noqa: BLE001 - caller sees it too
                self._telemetry.record_error(rec, error=repr(e))
                raise
            self._telemetry.record_finish(rec, n_tokens=max_new_tokens)
            return out

        # ------------------------------------------------------------
        # "continuous" scheduler: slot pool with mid-flight admission
        # ------------------------------------------------------------

        @staticmethod
        def _kv_heads(cfg):
            # llama GQA caches n_kv_head; gpt2 caches n_head
            return getattr(cfg, "n_kv_head", None) or cfg.n_head

        def _kv_shards(self) -> int:
            """How many ways the KV pool's head dim actually splits on
            the active mesh (1 when mesh-less or when the head count
            doesn't divide the tensor degree — the GQA guard)."""
            if self.mesh is None:
                return 1
            from ray_tpu.parallel.mesh import AXIS_TENSOR
            t = int(self.mesh.shape.get(AXIS_TENSOR, 1))
            return t if t > 1 and self._kv_heads(self.cfg) % t == 0 \
                else 1

        def _init_continuous(self, prefill_fn, step_fn, init_cache_fn,
                             init_paged_fn, paged_prefill_fn):
            import jax.numpy as jnp

            cfg = self.cfg
            self._pager = None
            if kv_layout == "paged":
                from ray_tpu.serve.kv_pager import BlockPager

                max_blk = cfg.max_seq // kv_block_size
                # default pool: every slot can hold a full sequence,
                # plus one sequence of headroom so the prefix cache and
                # COW forks survive a fully-occupied pool
                n_blocks = (kv_num_blocks if kv_num_blocks is not None
                            else 1 + (max_slots + 1) * max_blk)
                bytes_per_block = (2 * cfg.n_layer * kv_block_size
                                   * self._kv_heads(cfg)
                                   * cfg.head_dim
                                   * jnp.dtype(cfg.dtype).itemsize)
                self._pager = BlockPager(n_blocks, kv_block_size,
                                         cfg.max_seq,
                                         bytes_per_block=bytes_per_block,
                                         tensor_shards=self._kv_shards())
                self._cache = init_paged_fn(cfg, max_slots,
                                            num_blocks=n_blocks,
                                            block_size=kv_block_size,
                                            mesh=self.mesh)
            else:
                self._cache = init_cache_fn(cfg, max_slots,
                                            mesh=self.mesh)
            self._cur = np.zeros((max_slots,), np.int32)
            self._slots = [None] * max_slots
            self._queue = RequestQueue()
            self._wake = None           # asyncio.Event, made on-loop
            self._engine_task = None

            (self._prefill, self._paged_prefill, self._pool_step,
             self._admit, self._copy_block, self._clear_row) = \
                _jitted_engine_fns(prefill_fn, step_fn,
                                   paged_prefill_fn, cfg, temperature,
                                   kv_layout=kv_layout, mesh=self.mesh)
            # perf observatory: mirror process-wide program compile
            # events into this deployment's program-keyed recompile
            # counter (decode/sharded-decode shape churn visible, not
            # just prefill buckets); weak subscription — a retired
            # engine drops out of the registry automatically
            from ray_tpu._private.device_stats import get_registry

            get_registry().subscribe(
                self._telemetry.record_program_compile)

        def _admit_pending(self) -> None:
            """Prefill queued requests into free slots (one batched
            prefill dispatch each; K/V rows land in the pool cache).
            Paged layout: blocks are matched/allocated through the
            pager first — a request the pool cannot hold yet goes back
            to the queue HEAD and admission pauses until a retirement
            frees blocks."""
            import jax
            import jax.numpy as jnp

            while len(self._queue):
                free = [i for i, s in enumerate(self._slots)
                        if s is None]
                if not free:
                    return
                ((arr, rec), fut), = self._queue.pop(1)
                n = int(arr.shape[0])
                if n == 0 or n + max_new_tokens > self.cfg.max_seq:
                    self._telemetry.record_reject(
                        rec, reason=f"prompt length {n}",
                        label="oversized")
                    if not fut.done():
                        fut.set_exception(ValueError(
                            f"prompt length {n} invalid for "
                            f"max_seq={self.cfg.max_seq} with "
                            f"max_new_tokens={max_new_tokens}"))
                    continue
                slot = free[0]
                if self._pager is not None:
                    if not self._admit_one_paged(arr, rec, fut, slot):
                        return          # pool exhausted — retry later
                    continue
                # pad up to the bucket so the prefill program compiles
                # once per bucket; never past the decode headroom
                t_pad = -(-n // prefill_bucket) * prefill_bucket
                t_pad = max(n, min(t_pad,
                                   self.cfg.max_seq - max_new_tokens))
                self._telemetry.record_admit(rec, slot, t_pad)
                padded = np.zeros((1, t_pad), np.int32)
                padded[0, t_pad - n:] = arr
                self._rng, k = jax.random.split(self._rng)
                tok, row = self._prefill(
                    self.params, jnp.asarray(padded),
                    jnp.asarray([n], jnp.int32), k)
                # int() is the engine's existing host fence for the
                # prefill result; the timestamp behind it is the TTFT
                first = int(np.asarray(tok)[0])
                self._telemetry.record_first_token(rec)
                if max_new_tokens <= 1:
                    self._telemetry.record_finish(rec, n_tokens=1)
                    if not fut.done():
                        fut.set_result(np.concatenate(
                            [arr, np.asarray([first], np.int32)]))
                    continue
                self._cache = self._admit(self._cache, row, slot)
                self._cur[slot] = first
                self._slots[slot] = {"prompt": arr, "out": [first],
                                     "fut": fut, "rec": rec}

        def _admit_one_paged(self, arr, rec, fut, slot) -> bool:
            """Admit one request through the block pager: match the
            longest resident prompt prefix, allocate the remaining
            blocks up front (decode never allocates), COW-fork the
            write-boundary block if it is shared, then prefill only
            the unmatched tail.  Returns False when the pool cannot
            hold the request yet (request requeued at the head)."""
            import jax
            import jax.numpy as jnp

            pager = self._pager
            n = int(arr.shape[0])
            tokens = arr.tolist()
            need = pager.blocks_needed(n, max_new_tokens)
            prefix_len, matched = pager.match_prefix(tokens)
            alloc = pager.allocate(need - len(matched))
            if alloc is None:
                pager.release(matched)
                self._queue.push_front((arr, rec), fut)
                return False
            blocks = matched + alloc
            wb = prefix_len // kv_block_size
            if wb < len(matched):
                # the tail's first write lands inside a matched block
                try:
                    new_blk, src = pager.ensure_private(blocks[wb])
                except MemoryError:
                    pager.release(blocks)
                    self._queue.push_front((arr, rec), fut)
                    return False
                if src is not None:
                    blocks[wb] = new_blk
                    self._cache = self._copy_block(
                        self._cache, np.int32(src), np.int32(new_blk))
                    self._telemetry.record_cow()
            self._telemetry.record_prefix_reuse(
                len(matched), pager.blocks_needed(n, 0) - len(matched))
            n_tail = n - prefix_len
            t_pad = -(-n_tail // prefill_bucket) * prefill_bucket
            t_pad = max(n_tail, min(t_pad, self.cfg.max_seq))
            self._telemetry.record_admit(rec, slot, t_pad)
            tail_toks = np.zeros((1, t_pad), np.int32)
            tail_toks[0, t_pad - n_tail:] = arr[prefix_len:]
            row_bt = np.zeros((self.cfg.max_seq // kv_block_size,),
                              np.int32)
            row_bt[:len(blocks)] = blocks
            self._rng, k = jax.random.split(self._rng)
            tok, self._cache = self._paged_prefill(
                self.params, self._cache, jnp.asarray(tail_toks),
                jnp.asarray(row_bt), np.int32(prefix_len),
                np.int32(n_tail), np.int32(slot), k)
            # int() is the engine's existing host fence for the
            # prefill result; the timestamp behind it is the TTFT
            first = int(np.asarray(tok)[0])
            self._telemetry.record_first_token(rec)
            # the prompt's full blocks now hold exactly its K/V —
            # index them so later prompts can skip this work
            pager.register_prefix(tokens, blocks)
            if max_new_tokens <= 1:
                self._telemetry.record_finish(rec, n_tokens=1)
                if not fut.done():
                    fut.set_result(np.concatenate(
                        [arr, np.asarray([first], np.int32)]))
                self._retire_paged_row(slot, blocks)
                return True
            self._cur[slot] = first
            self._slots[slot] = {"prompt": arr, "out": [first],
                                 "fut": fut, "rec": rec,
                                 "blocks": blocks}
            self._telemetry.record_kv_stats(pager.stats())
            return True

        def _retire_paged_row(self, slot, blocks) -> None:
            """Free a finished/errored row's blocks.  The row's table
            is pointed at the null block FIRST: an idle row's decode
            step still scatter-writes (masked garbage), which must
            never land in a block the pager may re-hand out."""
            self._cache = self._clear_row(self._cache, np.int32(slot))
            self._pager.release(blocks)
            self._telemetry.record_kv_stats(self._pager.stats())

        async def _engine(self):
            """The scheduler loop: admit → one pooled decode step →
            retire finished slots → yield (so new requests enqueue
            mid-generation)."""
            import asyncio
            import time as _time

            import jax
            import jax.numpy as jnp

            while True:
                try:
                    self._admit_pending()
                    n_active = sum(s is not None for s in self._slots)
                    if not n_active:
                        self._wake.clear()
                        if not len(self._queue):
                            await self._wake.wait()
                        continue
                    # step walltime: dispatch + the np.asarray host
                    # fence the engine already performs — perf_counter
                    # pairs only, no extra device sync
                    t_step = _time.perf_counter()
                    self._rng, k = jax.random.split(self._rng)
                    toks, self._cache = self._pool_step(
                        self.params, self._cache,
                        jnp.asarray(self._cur), k)
                    # the engine's one deliberate per-step host fence
                    # (documented above; telemetry brackets it)
                    # graftcheck: disable=blocking-call-in-async
                    toks = np.asarray(toks)
                    self._telemetry.record_step(
                        n_active, _time.perf_counter() - t_step)
                    for i, st in enumerate(self._slots):
                        if st is None:
                            continue
                        st["out"].append(int(toks[i]))
                        self._cur[i] = toks[i]
                        if len(st["out"]) >= max_new_tokens:
                            self._telemetry.record_finish(
                                st["rec"], n_tokens=len(st["out"]))
                            if not st["fut"].done():
                                # st["out"] is a python int list — no
                                # device fetch here
                                # graftcheck: disable=blocking-call-in-async
                                tail = np.asarray(st["out"], np.int32)
                                st["fut"].set_result(np.concatenate(
                                    [st["prompt"], tail]))
                            self._slots[i] = None   # slot freed NOW
                            if self._pager is not None:
                                self._retire_paged_row(i, st["blocks"])
                except Exception as e:  # noqa: BLE001 - fail loudly
                    for i, st in enumerate(self._slots):
                        if st is not None:
                            self._telemetry.record_error(
                                st["rec"], error=repr(e))
                            if not st["fut"].done():
                                st["fut"].set_exception(e)
                            if self._pager is not None \
                                    and "blocks" in st:
                                self._pager.release(st["blocks"])
                        self._slots[i] = None
                    for (arr, rec), fut in self._queue.pop(
                            len(self._queue)):
                        self._telemetry.record_error(rec, error=repr(e))
                        if not fut.done():
                            fut.set_exception(e)
                # yield the loop so callers can enqueue mid-flight
                await asyncio.sleep(0)

        async def _call_continuous(self, prompt):
            import asyncio

            if self._wake is None:
                self._wake = asyncio.Event()
            if self._engine_task is None or self._engine_task.done():
                self._engine_task = asyncio.get_running_loop(
                ).create_task(self._engine())
            # host-side prompt normalization (python ints, no device
            # fetch) # graftcheck: disable=blocking-call-in-async
            arr = np.asarray(prompt, np.int32).reshape(-1)
            if admission_policy is not None:
                # the control loop: telemetry percentiles feed the
                # shed decision BEFORE the request costs the engine
                # anything
                shed = admission_policy.decide(
                    self._telemetry.engine_stats(), len(self._queue))
                if shed is not None:
                    rec = self._telemetry.record_enqueue(
                        int(arr.shape[0]))
                    self._telemetry.record_reject(
                        rec, reason=f"load shed: {shed}",
                        label=f"shed_{shed}")
                    raise OverloadedError(
                        f"request shed ({shed}): engine over SLO "
                        f"with {len(self._queue)} queued")
            rec = self._telemetry.record_enqueue(int(arr.shape[0]))
            fut = self._queue.put((arr, rec))
            self._wake.set()
            return await fut

        def shutdown_engine(self) -> None:
            """Stop the background engine task (direct-instance
            drivers — traffic generator, bench — call this so their
            event loop can close cleanly; serve replicas die with
            their actor process and never need it)."""
            task, self._engine_task = self._engine_task, None
            if task is not None and not task.done():
                task.cancel()

        # -- telemetry surface (works for both schedulers) -----------

        def engine_stats(self):
            """p50/p95/p99 TTFT + queue wait, throughput, slot
            utilization, request counts, rejections by reason, and
            (paged layout) the live kv_cache block/prefix-hit stats —
            `handle.method("engine_stats").remote()` or GET
            /api/serve/stats."""
            pager = getattr(self, "_pager", None)
            if pager is not None:
                self._telemetry.record_kv_stats(pager.stats())
            stats = self._telemetry.engine_stats()
            if admission_policy is not None:
                stats["admission_policy"] = admission_policy.describe()
            # perf observatory: compiled-cost / recompile / live-MFU
            # block for this engine's programs (process-wide registry,
            # filtered to the serve namespace)
            from ray_tpu._private.device_stats import (
                device_memory_stats, get_registry)

            mesh = getattr(self, "mesh", None)
            stats["programs"] = get_registry().snapshot(
                prefix="serve.",
                n_devices=int(mesh.size) if mesh is not None else 1)
            if mesh is not None:
                stats["mesh"] = {
                    "axes": {a: int(s)
                             for a, s in self.mesh.shape.items()
                             if int(s) > 1},
                    "n_devices": int(self.mesh.size),
                    "kv_shards": self._kv_shards(),
                    # per-chip allocator stats (stable keys; values
                    # are None on backends without memory_stats())
                    "devices": device_memory_stats(
                        list(self.mesh.devices.flat)),
                }
            return stats

        def export_timeline(self, path=None):
            """Chrome-trace engine timeline (queue lane, per-slot
            occupancy lanes, engine-step lane); writes `path` when
            given and returns the event list."""
            return self._telemetry.export_timeline(path)

        def metrics_snapshot(self):
            """This replica's serve_* metric dumps (histogram buckets
            included) straight from the process-local registry."""
            from ray_tpu.util.metrics import _registry

            return {name: dump for name, dump
                    in _registry.snapshot().items()
                    if name.startswith("serve_")}

    LLM.__call__ = (LLM._call_continuous if scheduler == "continuous"
                    else LLM._call_batch_traced)
    return deployment(name=f"llm_{family}_{preset}",
                      num_replicas=num_replicas)(LLM)
