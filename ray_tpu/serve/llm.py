"""LM serving: the model zoo's KV-cache decoders behind a batched
serve deployment.

No reference analog module (the reference serves user torch models);
this packages the composition its users hand-roll — model init or
checkpoint load, ONE jitted generate, @serve.batch micro-batching —
so `serve.run(build_llm_deployment(...).bind())` is a working LM
endpoint for either decoder family (gpt2 / llama).
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.serve.api import deployment


def build_llm_deployment(family: str = "gpt2", preset: str = "nano",
                         *, max_new_tokens: int = 16,
                         temperature: float = 0.0,
                         max_batch_size: int = 8,
                         batch_wait_timeout_s: float = 0.05,
                         checkpoint_path: Optional[str] = None,
                         seed: int = 0, num_replicas: int = 1,
                         config_overrides: Optional[Dict[str, Any]]
                         = None):
    """A serve Deployment generating continuations for equal-length
    int32 token-prompt arrays.

    family: "gpt2" | "llama"; preset: a model-zoo preset name.
    checkpoint_path: pickled param pytree (matching the family's init
    layout); absent → fresh init from `seed` (tests/demos)."""
    if family not in ("gpt2", "llama"):
        raise ValueError(f"unknown LM family {family!r}")

    @deployment(name=f"llm_{family}_{preset}",
                num_replicas=num_replicas)
    class LLM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            overrides = dict(config_overrides or {})
            if family == "gpt2":
                from ray_tpu.models import gpt2_config, gpt2_init
                from ray_tpu.models.gpt2_decode import generate

                self.cfg = gpt2_config(preset, **overrides)
                init_fn, gen_fn = gpt2_init, generate
            else:
                from ray_tpu.models import (llama_config,
                                            llama_generate,
                                            llama_init)

                self.cfg = llama_config(preset, **overrides)
                init_fn, gen_fn = llama_init, llama_generate
            if checkpoint_path:
                with open(checkpoint_path, "rb") as f:
                    self.params = jax.tree.map(jnp.asarray,
                                               pickle.load(f))
            else:
                self.params = init_fn(jax.random.PRNGKey(seed),
                                      self.cfg)
            # per-call PRNG threading: without it every temperature>0
            # request would sample under the same default key and
            # return identical "random" continuations
            self._rng = jax.random.PRNGKey(seed + 1)
            self._generate = jax.jit(
                lambda p, toks, k: gen_fn(
                    p, toks, self.cfg,
                    max_new_tokens=max_new_tokens,
                    temperature=temperature, key=k))

        from ray_tpu.serve.batching import batch as _batch

        @_batch(max_batch_size=max_batch_size,
                batch_wait_timeout_s=batch_wait_timeout_s)
        async def __call__(self, prompts):
            import jax
            import jax.numpy as jnp

            self._rng, k = jax.random.split(self._rng)
            toks = jnp.asarray(np.stack(prompts), jnp.int32)
            out = self._generate(self.params, toks, k)
            return [np.asarray(row) for row in out]

    return LLM
