"""LM serving: the model zoo's KV-cache decoders behind a serve
deployment.

No reference analog module (the reference serves user torch models);
this packages the composition its users hand-roll — model init or
checkpoint load, jitted prefill/decode programs, request batching —
so `serve.run(build_llm_deployment(...).bind())` is a working LM
endpoint for either decoder family (gpt2 / llama).

Two schedulers:

  * "batch" — @serve.batch micro-batching: concurrent requests are
    collected into one `generate` call and run TO COMPLETION together.
    Ragged prompt lists are LEFT-padded before stacking (the decode
    cache contract) and the pads trimmed from each returned row;
    equal-length batches keep the pad-free fast path (flash-eligible
    prefill).
  * "continuous" — slot-based continuous batching: a fixed pool of
    `max_slots` KV-cache rows.  Each admitted request gets ONE batched
    prefill dispatch into a free slot; all active slots then share one
    jitted decode step per token.  Finished sequences free their slot
    immediately and queued requests are admitted mid-flight — short
    requests are never held hostage by long ones, the failure mode of
    stack-and-pray fixed batching.  Prompt lengths are padded up to
    `prefill_bucket` multiples so the prefill program compiles once
    per bucket, not once per length.
"""

from __future__ import annotations

import dataclasses
import pickle
import types
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.models.decode_common import SamplingParams
from ray_tpu.serve.api import deployment
from ray_tpu.serve.batching import (ChunkCursor, HandoffCursor,
                                    OverloadedError,
                                    RequestQueue)
from ray_tpu.serve.batching import batch as _batch
from ray_tpu.serve.telemetry import EngineTelemetry


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knob for the continuous engine (round 11).

    draft: "ngram" (host-side zero-weight n-gram draft built from each
    request's own history) or "<family>:<preset>" (a small draft
    MODEL, e.g. "gpt2:nano" — its decode steps run in one jitted
    k+1-step scan per round).  k drafted tokens are verified per slot
    per round by ONE target verify dispatch, so at acceptance rate a
    the target runs ~1/(1 + a*k) dispatches per emitted token.
    draft_seed: PRNG seed for the draft model's init (None → the
    engine seed, so draft == target arch + preset + seed gives the
    perfectly aligned draft the CPU benches use).

    Frozen + hashable: part of the jitted-program cache key, so
    engines differing in k or draft can never alias one compiled
    program."""
    draft: str = "ngram"
    k: int = 4
    ngram_order: int = 2
    draft_seed: Optional[int] = None

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")
        if self.draft != "ngram":
            parts = self.draft.split(":")
            if len(parts) != 2 or parts[0] not in ("gpt2", "llama"):
                raise ValueError(
                    f"spec draft must be 'ngram' or "
                    f"'<family>:<preset>' with family gpt2|llama, "
                    f"got {self.draft!r}")
        if self.ngram_order < 1:
            raise ValueError(
                f"ngram_order must be >= 1, got {self.ngram_order}")


def _family_fns(family: str):
    """(config_fn, init_fn, generate_fn, prefill_fn, step_fn,
    init_cache_fn, init_paged_cache_fn, paged_prefill_fn,
    logical_axes_fn) for a decoder family."""
    if family == "gpt2":
        from ray_tpu.models import (gpt2_config, gpt2_init,
                                    gpt2_logical_axes)
        from ray_tpu.models.gpt2_decode import (decode_step, generate,
                                                init_cache,
                                                init_paged_cache,
                                                paged_prefill, prefill)

        return (gpt2_config, gpt2_init, generate, prefill, decode_step,
                init_cache, init_paged_cache, paged_prefill,
                gpt2_logical_axes)
    from ray_tpu.models import (llama_config, llama_init,
                                llama_logical_axes)
    from ray_tpu.models.llama_decode import (llama_decode_step,
                                             llama_generate,
                                             llama_init_cache,
                                             llama_init_paged_cache,
                                             llama_paged_prefill,
                                             llama_prefill)

    return (llama_config, llama_init, llama_generate, llama_prefill,
            llama_decode_step, llama_init_cache,
            llama_init_paged_cache, llama_paged_prefill,
            llama_logical_axes)


# jax's compile cache is keyed by the jitted function OBJECT, so a
# fresh `jax.jit(closure)` per engine instance recompiles every
# program for every instance — pathological for test suites and
# notebooks that build many short-lived engines.  The continuous
# engine's programs depend only on (family fns, config, sampling
# config, kv layout, mesh, spec config + draft fns); configs /
# SamplingParams / SpecConfig are frozen dataclasses and jax Meshes
# are hashable by (axis names, device assignment), so equal-config
# engines can share ONE set of jitted callables and therefore one
# compile — while engines that differ in ANY closure input (layout,
# mesh, a sampling knob, spec k, the draft) get their own entries
# instead of aliasing a stale compiled program (round-11 regression:
# the key once carried only `temperature`, so a top_k change or a
# different spec k would silently reuse the old sampler).
_JIT_CACHE: Dict[Any, Any] = {}


def _jitted_engine_fns(prefill_fn, step_fn, paged_prefill_fn, cfg,
                       sampling, kv_layout="dense", mesh=None,
                       spec=None, verify_fn=None, draft_fns=None):
    """Namespace of jitted programs for one engine identity:

      prefill / paged_prefill / pool_step  — fused sample-included
          programs (engine-default sampling baked in; the hot path
          stays one dispatch)
      prefill_raw / paged_prefill_raw / pool_logits — logits-returning
          twins for requests overriding SamplingParams (compiled only
          if such a request arrives)
      admit / copy_block / clear_row       — pool bookkeeping
      spec_verify                          — (spec only) ONE target
          dispatch verifying a (B, k+1) draft block, KV donated
      draft_propose                        — (model draft only) the
          k+1-step draft scan

    `sampling` is a SamplingParams (a bare float is accepted as
    temperature-only for backward compatibility).  The cache key
    carries the FULL sampling + spec identity."""
    if not isinstance(sampling, SamplingParams):
        sampling = SamplingParams(temperature=float(sampling))
    key = (prefill_fn, step_fn, paged_prefill_fn, cfg, sampling,
           kv_layout, mesh, spec, verify_fn, draft_fns)
    cached = _JIT_CACHE.get(key)
    if cached is not None:
        return cached
    import jax
    from jax import lax

    from ray_tpu.models.decode_common import (copy_block,
                                              make_draft_propose,
                                              make_spec_verify,
                                              make_vocab_tail_mask,
                                              sample_token)

    tail = make_vocab_tail_mask(cfg)
    temperature = sampling.temperature
    top_k, top_p = sampling.top_k, sampling.top_p

    def prefill_sample(p, toks, lens, k):
        logits, cache = prefill_fn(p, toks, cfg, lengths=lens)
        return sample_token(logits, k, temperature, tail, top_k,
                            top_p), cache

    def prefill_raw(p, toks, lens):
        return prefill_fn(p, toks, cfg, lengths=lens)

    def paged_prefill_sample(p, cache, toks, row_bt, prefix_len,
                             n_tail, slot, k):
        logits, cache = paged_prefill_fn(
            p, cache, toks, cfg, row_bt=row_bt,
            prefix_len=prefix_len, n_tail=n_tail, slot=slot)
        return sample_token(logits[None], k, temperature, tail,
                            top_k, top_p), cache

    def paged_prefill_raw(p, cache, toks, row_bt, prefix_len, n_tail,
                          slot):
        logits, cache = paged_prefill_fn(
            p, cache, toks, cfg, row_bt=row_bt,
            prefix_len=prefix_len, n_tail=n_tail, slot=slot)
        return logits[None], cache

    def pool_step(p, cache, toks, k):
        logits, cache = step_fn(p, cache, toks, cfg)
        return sample_token(logits, k, temperature, tail, top_k,
                            top_p), cache

    def pool_logits(p, cache, toks):
        return step_fn(p, cache, toks, cfg)

    def admit(pool, row, slot):
        out = dict(pool)
        for name in ("k", "v"):   # (L, B, S, ...): row b=slot
            out[name] = lax.dynamic_update_slice_in_dim(
                pool[name], row[name], slot, axis=1)
        for name in ("pos", "start"):
            out[name] = lax.dynamic_update_slice_in_dim(
                pool[name], row[name], slot, axis=0)
        return out

    def clear_row(cache, slot):
        # retire a row: its table points at the null block so the
        # (masked, unread) writes of an idle row can never land in a
        # block the pager has handed to someone else
        out = dict(cache)
        out["block_tables"] = cache["block_tables"].at[slot].set(0)
        out["pos"] = cache["pos"].at[slot].set(0)
        return out

    def install_blocks(cache, blk_ids, k_stack, v_stack):
        # tiered host-RAM KV cache (serve/kv_tier.py): splice a whole
        # restored chain back into the pool in ONE dispatch — blk_ids
        # is a fixed-length (max_seq // block_size) id vector and the
        # stacks are (N, L, block_size, H, head_dim) rows, so every
        # restore shares one compiled program regardless of chain
        # length.  Padding entries target the null block (id 0) with
        # zero rows: block 0 is the masked write-sink idle rows
        # already scribble into, so the pad write is harmless by the
        # same contract.  The pool is donated — a restore must never
        # copy a multi-GB pool just to overwrite a few blocks.  On a
        # sharded pool the committed cache shardings re-distribute
        # the replicated host rows, mirroring how admit() lands rows.
        out = dict(cache)
        out["k"] = cache["k"].at[:, blk_ids].set(
            k_stack.swapaxes(0, 1))
        out["v"] = cache["v"].at[:, blk_ids].set(
            v_stack.swapaxes(0, 1))
        return out

    def save_block(cache, blk):
        # spill companion to install_blocks: one fused program slices
        # a block's K and V rows out of the pool together, so an
        # eviction costs a single dispatch + one D2H transfer pair
        # instead of two eager slice ops (the spill path runs once per
        # eviction — at small block counts that is hundreds of times a
        # run, and per-op overhead is the whole cost on host backends)
        return cache["k"][:, blk], cache["v"][:, blk]

    def kv_handoff_export(cache, blk_ids):
        # disaggregated prefill→decode handoff (serve/router.py
        # two-stage dispatch): gather a finished prefill's filled
        # block rows out of the pool in ONE dispatch — the read twin
        # of install_blocks, sharing its fixed-length id-vector shape
        # so every handoff reuses one compiled program.  Pad entries
        # (id 0) gather the null block's garbage rows; they install
        # back into the null block on the decode side, so the pads
        # are harmless end to end by the same write-sink contract.
        return (cache["k"][:, blk_ids].swapaxes(0, 1),
                cache["v"][:, blk_ids].swapaxes(0, 1))

    def kv_handoff_install(cache, blk_ids, k_stack, v_stack, slot,
                           row_bt, pos):
        # decode-side handoff splice: land the exported rows AND
        # point the receiving row's block table / pos / start at them
        # in ONE donated dispatch, so the row is decode-ready the
        # moment the program retires and the first decode step reads
        # exactly the rows the prefill replica wrote (bit-identical
        # to the monolithic engine by construction).  `pos` is the
        # prompt length — the same value paged_prefill leaves behind
        # (prefix_len + n_tail) — and start pins to 0 like every
        # paged admission.
        out = dict(cache)
        out["k"] = cache["k"].at[:, blk_ids].set(
            k_stack.swapaxes(0, 1))
        out["v"] = cache["v"].at[:, blk_ids].set(
            v_stack.swapaxes(0, 1))
        out["block_tables"] = cache["block_tables"].at[slot].set(
            row_bt)
        out["pos"] = cache["pos"].at[slot].set(pos)
        out["start"] = cache["start"].at[slot].set(0)
        return out

    # perf observatory: the heavy programs report compiles / compiler
    # cost model / invoke walltimes to the process-wide registry under
    # stable names (sharded engines get their own so single- and
    # multi-chip cost models never mix)
    from ray_tpu._private.device_stats import get_registry

    registry = get_registry()
    shard = "serve.sharded_" if mesh is not None else "serve."
    n_dev = len(getattr(mesh, "devices", [[None]]).flat) \
        if mesh is not None else 1
    spec_verify = draft_propose = draft_prefill = None
    if spec is not None:
        verify = make_spec_verify(verify_fn, cfg,
                                  temperature=temperature,
                                  top_k=top_k, top_p=top_p)
        # the target KV pool (arg 1) is donated: the verify round is
        # the engine's steady-state hot program and the old pool is
        # dead the moment the new one lands
        spec_verify = registry.instrument(
            shard + "spec_verify",
            jax.jit(verify, donate_argnums=(1,)), n_dev)
        if draft_fns is not None:
            d_prefill_fn, d_step_fn, d_cfg = draft_fns
            d_tail = make_vocab_tail_mask(d_cfg)
            propose = make_draft_propose(
                d_step_fn, d_cfg, spec.k, temperature=temperature,
                top_k=top_k, top_p=top_p,
                with_probs=temperature > 0.0)
            draft_propose = registry.instrument(
                shard + "spec_draft", jax.jit(propose), n_dev)

            def d_prefill(p, toks, lens, k):
                logits, cache = d_prefill_fn(p, toks, d_cfg,
                                             lengths=lens)
                return sample_token(logits, k, temperature, d_tail,
                                    top_k, top_p), cache

            draft_prefill = jax.jit(d_prefill)
    fns = types.SimpleNamespace(
        prefill=registry.instrument(shard + "prefill",
                                    jax.jit(prefill_sample), n_dev),
        paged_prefill=registry.instrument(
            shard + "paged_prefill", jax.jit(paged_prefill_sample),
            n_dev),
        pool_step=registry.instrument(shard + "decode",
                                      jax.jit(pool_step), n_dev),
        prefill_raw=jax.jit(prefill_raw),
        paged_prefill_raw=jax.jit(paged_prefill_raw),
        pool_logits=jax.jit(pool_logits),
        admit=jax.jit(admit), copy_block=jax.jit(copy_block),
        clear_row=jax.jit(clear_row),
        install_blocks=jax.jit(install_blocks, donate_argnums=(0,)),
        save_block=jax.jit(save_block),
        kv_handoff_export=registry.instrument(
            shard + "kv_handoff_export", jax.jit(kv_handoff_export),
            n_dev),
        kv_handoff_install=registry.instrument(
            shard + "kv_handoff_install",
            jax.jit(kv_handoff_install, donate_argnums=(0,)), n_dev),
        spec_verify=spec_verify, draft_propose=draft_propose,
        draft_prefill=draft_prefill)
    _JIT_CACHE[key] = fns
    return fns


def build_llm_deployment(family: str = "gpt2", preset: str = "nano",
                         *, max_new_tokens: int = 16,
                         temperature: float = 0.0,
                         top_k: int = 0, top_p: float = 1.0,
                         stop_sequences=None,
                         eos_id: Optional[int] = None,
                         max_batch_size: int = 8,
                         batch_wait_timeout_s: float = 0.05,
                         checkpoint_path: Optional[str] = None,
                         seed: int = 0, num_replicas: int = 1,
                         scheduler: str = "batch",
                         max_slots: int = 4,
                         prefill_bucket: int = 16,
                         kv_layout: str = "dense",
                         kv_block_size: int = 16,
                         kv_num_blocks: Optional[int] = None,
                         prefill_chunk_tokens: Optional[int] = None,
                         kv_host_tier_bytes: Optional[int] = None,
                         admission_policy=None,
                         slo=None,
                         mesh=None,
                         spec_decode: Optional[SpecConfig] = None,
                         role: str = "both",
                         handoff_staged: bool = False,
                         config_overrides: Optional[Dict[str, Any]]
                         = None):
    """A serve Deployment generating continuations for int32
    token-prompt arrays (1-D per request; ragged lengths welcome —
    each caller gets back its own prompt + continuation, pads
    trimmed).

    family: "gpt2" | "llama"; preset: a model-zoo preset name.
    scheduler: "batch" (@serve.batch fixed micro-batches) or
    "continuous" (slot pool of `max_slots` KV rows with mid-flight
    admission; `prefill_bucket` bounds prefill recompiles).
    kv_layout: "dense" (per-slot rows, the parity oracle) or "paged"
    (shared block pool + per-row block tables managed by
    serve/kv_pager.py — prompt prefixes resident from earlier requests
    are reused instead of re-prefilled, with copy-on-write forks at
    shared write boundaries).  kv_block_size sets the block token
    granularity; kv_num_blocks the pool size (default: enough for
    every slot plus one sequence of prefix-cache headroom).
    prefill_chunk_tokens: chunked streaming prefill (paged layout
    only; dense keeps one-shot prefill as the bit-exactness oracle).
    A prompt whose unmatched tail exceeds N tokens is admitted as a
    sequence of block-aligned prefill chunks interleaved with decode
    waves — the engine loop alternates `decode wave → at most one
    chunk of pending prefill → decode wave`, with round-robin
    fairness over chunking slots so one huge prompt cannot consume
    consecutive chunk windows.  Each chunk is a call to the existing
    paged_prefill program with prefix_len = tokens already filled
    (prior chunks are literally resident prefix blocks), so chunked
    output is bit-identical to one-shot prefill by construction and
    the program compiles once per prefill_bucket-padded chunk shape.
    Must be a positive multiple of kv_block_size.  None (default)
    keeps one-shot prefill.
    kv_host_tier_bytes: tiered host-RAM KV cache (paged layout only;
    serve/kv_tier.py).  When set, a prefix block the pager's LRU
    eviction claims is spilled device→host into a byte-budgeted
    LRU store under its content-addressed key, and an admission whose
    HBM prefix match falls short probes that store second-chance: a
    hit re-installs the block via one H2D copy + block-table splice
    and bumps prefix_len so paged_prefill skips those tokens — the
    effective prefix cache grows beyond HBM and re-admitted prefixes
    cost a copy instead of a re-prefill (outputs stay bit-identical
    to the dense oracle; the restore rows ARE the rows prefill would
    write).  Surfaced as engine_stats()["kv_tier"], tracebus
    `kv.fetch` spans, and the `kv_fetch_ms` critical-path component.
    None (default) keeps plain discard-on-evict.
    admission_policy: a serve.batching.AdmissionPolicy closing the
    telemetry loop — requests are load-shed with OverloadedError when
    its queue-depth / queue-wait / TTFT gates trip.
    slo: a serve.slo.SLOConfig (continuous scheduler only) turning the
    telemetry stream into multi-window burn rates —
    engine_stats()["slo"], serve_slo_* metrics, and an anomaly
    watchdog that postmortem-dumps the engine's flight record
    (_private/flightrec.py) on burn-rate breaches and recompile
    storms.  Without it engine_stats()["slo"] is None; the flight
    recorder itself is always on (RAYTPU_FLIGHTREC=0 disables).
    mesh: a `jax.sharding.Mesh` to tensor-parallelise the engine over
    (continuous scheduler only).  Params and the KV pool are committed
    to the mesh under parallel.sharding.DECODE_RULES — attention
    heads, MLP hidden, lm-head vocab, and the pool's KV-head dim split
    over the `tensor` axis (dims the degree doesn't divide replicate);
    the committed input shardings propagate through the existing
    jitted programs, so one pool step spans all chips.  Block tables
    and the BlockPager stay host-side and layout-agnostic.  None (the
    default) keeps today's single-device behaviour.
    top_k / top_p: engine-default nucleus knobs composed with
    `temperature` (jit-static, baked into the fused sample-included
    programs).  Continuous-scheduler callers may override per request
    with `handle.remote(prompt, sampling=SamplingParams(...))` — the
    engine routes those slots through a logits-returning twin program
    plus a per-SamplingParams jitted sampler, so the default hot path
    stays one fused dispatch.
    stop_sequences / eos_id: host-side stop matching on the GENERATED
    tokens (continuous scheduler): a slot whose tail matches any stop
    sequence (or whose last token == eos_id) finishes immediately,
    freeing its slot (and paged blocks) mid-flight for the next queued
    request — generation never burns the full max_new_tokens budget on
    a sequence that already ended.
    spec_decode: a SpecConfig enabling speculative decoding on the
    continuous engine — a draft (n-gram or small model) proposes k
    tokens per slot per round and ONE jitted target verify dispatch
    checks all k+1 positions, so at acceptance rate a the target runs
    ~1/(1 + a*k) dispatches per emitted token.  Greedy (temperature 0)
    spec output is bit-identical to the non-speculative engine.
    role: disaggregated prefill/decode serving (round 18).  "both"
    (default) is the monolithic engine.  "prefill" engines run the
    admission + prefill machinery only and PARK at the handoff: when a
    request's last chunk finishes, the filled KV block rows are
    exported (one fixed-shape kv_handoff_export gather) and the
    request's future resolves with a serve.batching.HandoffCursor
    instead of tokens — the fleet router forwards it to a decode
    replica.  "decode" engines accept those cursors through
    ``admit_prefilled``: fresh blocks are allocated, the rows land via
    one donated kv_handoff_install splice (block table + pos + start
    set in the same dispatch), and decoding resumes at the prefill
    replica's first token — bit-identical to the monolithic engine by
    construction.  Both split roles require scheduler='continuous'
    and kv_layout='paged'.
    handoff_staged: force the staged D2H→H2D handoff hop (the general
    cross-process path — export rows are pulled to host before the
    decode-side install) even when prefill and decode replicas share
    one process.  Default False keeps the same-process fast path,
    where the exported rows stay device-resident end to end.
    checkpoint_path: pickled param pytree (matching the family's init
    layout); absent → fresh init from `seed` (tests/demos)."""
    if family not in ("gpt2", "llama"):
        raise ValueError(f"unknown LM family {family!r}")
    if scheduler not in ("batch", "continuous"):
        raise ValueError(f"unknown scheduler {scheduler!r} "
                         f"(expected 'batch' or 'continuous')")
    if kv_layout not in ("dense", "paged"):
        raise ValueError(f"unknown kv_layout {kv_layout!r} "
                         f"(expected 'dense' or 'paged')")
    if kv_layout == "paged" and scheduler != "continuous":
        raise ValueError("kv_layout='paged' requires "
                         "scheduler='continuous' (the block pager "
                         "lives in the continuous engine)")
    if prefill_chunk_tokens is not None:
        if kv_layout != "paged":
            raise ValueError(
                "prefill_chunk_tokens requires kv_layout='paged' "
                "(chunks fill KV blocks incrementally through "
                "paged_prefill; dense keeps one-shot prefill as the "
                "bit-exactness oracle)")
        if prefill_chunk_tokens < 1 \
                or prefill_chunk_tokens % kv_block_size:
            raise ValueError(
                f"prefill_chunk_tokens={prefill_chunk_tokens} must be "
                f"a positive multiple of kv_block_size="
                f"{kv_block_size} (chunks must end on block "
                "boundaries so prior chunks are resident prefix "
                "blocks)")
    if kv_host_tier_bytes is not None:
        if kv_layout != "paged":
            raise ValueError(
                "kv_host_tier_bytes requires kv_layout='paged' (the "
                "host tier spills and restores the pager's KV "
                "blocks; dense rows are never evicted)")
        if int(kv_host_tier_bytes) <= 0:
            raise ValueError(
                f"kv_host_tier_bytes={kv_host_tier_bytes} must be a "
                "positive byte budget")
    if role not in ("both", "prefill", "decode"):
        raise ValueError(f"unknown role {role!r} (expected 'both', "
                         "'prefill', or 'decode')")
    if role != "both":
        if scheduler != "continuous":
            raise ValueError(
                f"role={role!r} requires scheduler='continuous' "
                "(the handoff parks/admits through the slot-pool "
                "engine loop)")
        if kv_layout != "paged":
            raise ValueError(
                f"role={role!r} requires kv_layout='paged' (the "
                "handoff moves block rows between pagers; dense rows "
                "have no block-granular identity to hand off)")
    if handoff_staged and role == "both":
        raise ValueError(
            "handoff_staged only applies to split roles "
            "(role='prefill' exports through host staging; a "
            "monolithic engine never hands off)")
    if mesh is not None and scheduler != "continuous":
        raise ValueError("mesh-sharded serving requires "
                         "scheduler='continuous' (the batch scheduler "
                         "is single-device)")
    if spec_decode is not None:
        if not isinstance(spec_decode, SpecConfig):
            raise ValueError("spec_decode must be a SpecConfig, got "
                             f"{type(spec_decode).__name__}")
        if scheduler != "continuous":
            raise ValueError("spec_decode requires "
                             "scheduler='continuous' (speculation "
                             "lives in the slot-pool engine loop)")
    if slo is not None:
        from ray_tpu.serve.slo import SLOConfig
        if not isinstance(slo, SLOConfig):
            raise ValueError("slo must be a serve.slo.SLOConfig, got "
                             f"{type(slo).__name__}")
        if scheduler != "continuous":
            raise ValueError("slo requires scheduler='continuous' "
                             "(the burn-rate watchdog runs from the "
                             "slot-pool engine loop)")
    # validates the knobs (and is the engine's default per-request
    # params — requests that don't override sample through the fused
    # programs this bakes in)
    default_sp = SamplingParams(temperature=temperature, top_k=top_k,
                                top_p=top_p)
    stop_seqs = tuple(
        tuple(int(t) for t in np.asarray(s, np.int64).reshape(-1))
        for s in (stop_sequences or ()))
    if any(len(s) == 0 for s in stop_seqs):
        raise ValueError("empty stop sequence")

    class LLM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            overrides = dict(config_overrides or {})
            (config_fn, init_fn, gen_fn, prefill_fn, step_fn,
             init_cache_fn, init_paged_fn, paged_prefill_fn,
             logical_axes_fn) = _family_fns(family)
            self.cfg = config_fn(preset, **overrides)
            if checkpoint_path:
                with open(checkpoint_path, "rb") as f:
                    self.params = jax.tree.map(jnp.asarray,
                                               pickle.load(f))
            else:
                self.params = init_fn(jax.random.PRNGKey(seed),
                                      self.cfg)
            self.mesh = mesh
            if mesh is not None:
                # commit params to the mesh once at construction; the
                # committed shardings propagate through every jitted
                # program below, turning them SPMD without annotation
                from ray_tpu.parallel.sharding import (DECODE_RULES,
                                                       shard_by_shape)
                self.params = shard_by_shape(
                    self.params, logical_axes_fn(self.cfg), mesh,
                    DECODE_RULES)
            # per-call PRNG threading: without it every temperature>0
            # request would sample under the same default key and
            # return identical "random" continuations
            self._rng = jax.random.PRNGKey(seed + 1)
            # host-side lifecycle telemetry (enqueue/admit/first-token/
            # step/finish records -> metrics + engine_stats + timeline);
            # never touches the jitted programs
            self._telemetry = EngineTelemetry(
                f"llm_{family}_{preset}",
                max_slots=(max_slots if scheduler == "continuous"
                           else max_batch_size),
                role=role)
            #: disaggregated serving role — the fleet router reads
            #: this to type replicas ("prefill" | "decode" | "both")
            self.role = role
            #: round-19 healthwatch/chaos attach points — the fleet
            #: (serve/router.py LLMFleet) overwrites these after
            #: construction; standalone engines keep them None, so
            #: the engine loop's only cost is one `is None` check
            #: per wave
            self._health = None
            self._chaos = None
            self._replica_label = f"llm_{family}_{preset}"
            if scheduler == "batch":
                self._generate = jax.jit(
                    lambda p, toks, k: gen_fn(
                        p, toks, self.cfg,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, key=k))
                self._generate_ragged = jax.jit(
                    lambda p, toks, lens, k: gen_fn(
                        p, toks, self.cfg, lengths=lens,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, key=k))
            else:
                self._init_continuous(prefill_fn, step_fn,
                                      init_cache_fn, init_paged_fn,
                                      paged_prefill_fn)

        # ------------------------------------------------------------
        # "batch" scheduler: @serve.batch over (possibly ragged) lists
        # ------------------------------------------------------------

        @_batch(max_batch_size=max_batch_size,
                batch_wait_timeout_s=batch_wait_timeout_s)
        async def _call_batch(self, prompts):
            import jax
            import jax.numpy as jnp

            self._rng, k = jax.random.split(self._rng)
            # host-side prompt normalization (python ints, no device fetch)
            # graftcheck: disable=blocking-call-in-async(host-side int normalization)
            arrs = [np.asarray(p, np.int32).reshape(-1)
                    for p in prompts]
            lens = [int(a.shape[0]) for a in arrs]
            t0 = max(lens)
            if min(lens) == t0:
                # equal-length fast path: no pads, flash-eligible
                toks = jnp.asarray(np.stack(arrs), jnp.int32)
                out = self._generate(self.params, toks, k)
                # the batch is done on device and callers need host arrays
                # graftcheck: disable=blocking-call-in-async(deliberate result fetch)
                return [np.asarray(row) for row in out]
            padded = np.zeros((len(arrs), t0), np.int32)
            for i, a in enumerate(arrs):
                padded[i, t0 - lens[i]:] = a
            out = self._generate_ragged(
                self.params, jnp.asarray(padded),
                jnp.asarray(lens, jnp.int32), k)
            # trim the left pads: each caller sees prompt+continuation
            # graftcheck: disable=blocking-call-in-async(deliberate result fetch)
            return [np.asarray(row)[t0 - n:]
                    for row, n in zip(out, lens)]

        async def _call_batch_traced(self, prompt, sampling=None):
            if sampling is not None:
                raise ValueError(
                    "per-request sampling requires "
                    "scheduler='continuous' (the batch scheduler runs "
                    "one fused generate per micro-batch)")
            # request-level telemetry wraps the @serve.batch queue so
            # the recorded latency includes the batch-collection wait
            # prompt is a host-side list; its length moves no device data
            # graftcheck: disable=blocking-call-in-async(host-side length probe)
            n_prompt = int(np.asarray(prompt).reshape(-1).shape[0])
            rec = self._telemetry.record_enqueue(n_prompt)
            if n_prompt == 0 or \
                    n_prompt + max_new_tokens > self.cfg.max_seq:
                # pre-validate BEFORE batching: an oversized prompt
                # used to blow up the whole micro-batch from inside
                # generate (and bypassed the rejection metrics lane)
                self._telemetry.record_reject(
                    rec, reason=f"prompt length {n_prompt}",
                    label="oversized")
                raise ValueError(
                    f"prompt length {n_prompt} invalid for "
                    f"max_seq={self.cfg.max_seq} with "
                    f"max_new_tokens={max_new_tokens}")
            try:
                out = await self._call_batch(prompt)
            except Exception as e:  # noqa: BLE001 - caller sees it too
                self._telemetry.record_error(rec, error=repr(e))
                raise
            self._telemetry.record_finish(rec, n_tokens=max_new_tokens)
            return out

        # ------------------------------------------------------------
        # "continuous" scheduler: slot pool with mid-flight admission
        # ------------------------------------------------------------

        @staticmethod
        def _kv_heads(cfg):
            # llama GQA caches n_kv_head; gpt2 caches n_head
            return getattr(cfg, "n_kv_head", None) or cfg.n_head

        def _kv_shards(self) -> int:
            """How many ways the KV pool's head dim actually splits on
            the active mesh (1 when mesh-less or when the head count
            doesn't divide the tensor degree — the GQA guard)."""
            if self.mesh is None:
                return 1
            from ray_tpu.parallel.mesh import AXIS_TENSOR
            t = int(self.mesh.shape.get(AXIS_TENSOR, 1))
            return t if t > 1 and self._kv_heads(self.cfg) % t == 0 \
                else 1

        def _init_continuous(self, prefill_fn, step_fn, init_cache_fn,
                             init_paged_fn, paged_prefill_fn):
            import jax
            import jax.numpy as jnp

            cfg = self.cfg
            self._pager = None
            if kv_layout == "paged":
                from ray_tpu.serve.kv_pager import BlockPager

                max_blk = cfg.max_seq // kv_block_size
                # default pool: every slot can hold a full sequence,
                # plus one sequence of headroom so the prefix cache and
                # COW forks survive a fully-occupied pool
                n_blocks = (kv_num_blocks if kv_num_blocks is not None
                            else 1 + (max_slots + 1) * max_blk)
                bytes_per_block = (2 * cfg.n_layer * kv_block_size
                                   * self._kv_heads(cfg)
                                   * cfg.head_dim
                                   * jnp.dtype(cfg.dtype).itemsize)
                # tiered host-RAM KV cache: evicted prefix blocks
                # spill device→host and re-admit via H2D copy instead
                # of re-prefill (serve/kv_tier.py)
                host_tier = None
                if kv_host_tier_bytes is not None:
                    from ray_tpu.serve.kv_tier import HostKVTier

                    host_tier = HostKVTier(kv_host_tier_bytes)
                self._pager = BlockPager(
                    n_blocks, kv_block_size, cfg.max_seq,
                    bytes_per_block=bytes_per_block,
                    tensor_shards=self._kv_shards(),
                    recorder=self._telemetry.flightrec,
                    host_tier=host_tier)
                self._cache = init_paged_fn(cfg, max_slots,
                                            num_blocks=n_blocks,
                                            block_size=kv_block_size,
                                            mesh=self.mesh)
                if host_tier is not None:
                    self._pager.set_block_saver(self._tier_save)
            else:
                self._cache = init_cache_fn(cfg, max_slots,
                                            mesh=self.mesh)
            self._cur = np.zeros((max_slots,), np.int32)
            self._slots = [None] * max_slots
            self._queue = RequestQueue()
            self._wake = None           # asyncio.Event, made on-loop
            self._engine_task = None
            self._default_sp = default_sp
            self._samplers = {}     # SamplingParams -> jitted sampler
            # chunked streaming prefill (round 15): round-robin cursor
            # over slots mid-prefill, plus a constant key for the
            # discarded samples of intermediate chunks (the engine RNG
            # splits once per admission, at the FINAL chunk — the same
            # stream a one-shot admission sees)
            self._chunk_rr = 0
            self._dummy_key = None
            if prefill_chunk_tokens is not None:
                import jax as _jax
                self._dummy_key = _jax.random.PRNGKey(0)

            # spec decode: resolve the verify program and (model
            # drafts) the draft family's fns/config/params/cache pool
            verify_fn = draft_fns = None
            self._draft_params = self._draft_cache = None
            self._draft_cfg = None
            self._spec_sampled = (spec_decode is not None
                                  and temperature > 0.0)
            if spec_decode is not None:
                if family == "gpt2":
                    from ray_tpu.models.gpt2_decode import verify_step
                    verify_fn = verify_step
                else:
                    from ray_tpu.models.llama_decode import \
                        llama_verify_step
                    verify_fn = llama_verify_step
                # draft rewind bookkeeping: per slot, how many of last
                # round's drafted tokens the target rejected (the
                # draft cache rolls back exactly this many positions
                # at the top of the next propose dispatch)
                self._spec_rej = np.zeros((max_slots,), np.int32)
                if spec_decode.draft != "ngram":
                    d_family, d_preset = spec_decode.draft.split(":")
                    (d_config_fn, d_init_fn, _g, d_prefill_fn,
                     d_step_fn, d_init_cache_fn, *_rest) = \
                        _family_fns(d_family)
                    # overrides describe THIS family's config fields;
                    # a cross-family draft takes its preset verbatim
                    d_over = (dict(config_overrides or {})
                              if d_family == family else {})
                    d_cfg = d_config_fn(d_preset, **d_over)
                    if (d_cfg.vocab_size != cfg.vocab_size
                            or d_cfg.padded_vocab != cfg.padded_vocab):
                        raise ValueError(
                            f"spec draft vocab "
                            f"{d_cfg.vocab_size}/{d_cfg.padded_vocab} "
                            f"!= target "
                            f"{cfg.vocab_size}/{cfg.padded_vocab} — "
                            "draft proposals index the target vocab")
                    if d_cfg.max_seq < cfg.max_seq:
                        raise ValueError(
                            f"spec draft max_seq {d_cfg.max_seq} < "
                            f"target max_seq {cfg.max_seq} — the "
                            "draft cache must track every target "
                            "position")
                    d_seed = (spec_decode.draft_seed
                              if spec_decode.draft_seed is not None
                              else seed)
                    import jax as _jax
                    self._draft_params = d_init_fn(
                        _jax.random.PRNGKey(d_seed), d_cfg)
                    # draft pool: always dense, never mesh-sharded —
                    # the draft is small by construction and a dense
                    # row pool keeps its pos arithmetic trivial
                    self._draft_cache = d_init_cache_fn(d_cfg,
                                                        max_slots)
                    self._draft_cfg = d_cfg
                    draft_fns = (d_prefill_fn, d_step_fn, d_cfg)

            fns = _jitted_engine_fns(
                prefill_fn, step_fn, paged_prefill_fn, cfg,
                default_sp, kv_layout=kv_layout, mesh=self.mesh,
                spec=spec_decode, verify_fn=verify_fn,
                draft_fns=draft_fns)
            self._fns = fns
            (self._prefill, self._paged_prefill, self._pool_step,
             self._admit, self._copy_block, self._clear_row) = (
                fns.prefill, fns.paged_prefill, fns.pool_step,
                fns.admit, fns.copy_block, fns.clear_row)
            if self._pager is not None and self._pager.tier is not None:
                # pre-compile the H2D splice program with an all-pad
                # call (every id 0 → zero rows into the null write
                # sink): restores share ONE fixed-shape program, so
                # the first real tier restore pays a copy inside its
                # kv_fetch window, not a compile
                from ray_tpu.serve.kv_tier import staging_buffers

                maxn = cfg.max_seq // kv_block_size
                row_shape = (maxn,) + self._cache["k"][:, 0].shape
                row_dtype = self._cache["k"].dtype
                # persistent host staging buffers for the restore path
                # (ids, k rows, v rows) — refilled in place per
                # restore instead of re-allocating pad arrays
                self._tier_stage = staging_buffers(maxn, row_shape,
                                                   row_dtype)
                zr = jnp.zeros(row_shape, self._cache["k"].dtype)
                self._cache = fns.install_blocks(
                    self._cache, jnp.zeros((maxn,), jnp.int32),
                    zr, zr)
                jax.block_until_ready(self._cache["k"])
            if self._pager is not None:
                # handoff id staging buffer: role-split engines use it
                # every handoff; a role="both" engine only if a caller
                # feeds it packages via admit_prefilled directly
                self._handoff_ids = np.zeros(
                    (cfg.max_seq // kv_block_size,), np.int32)
            if role != "both":
                # disaggregated handoff: pre-compile this role's side
                # of the block move with an all-pad call so the first
                # real handoff pays a copy inside its handoff window,
                # not an XLA compile (the tier-splice precompile
                # discipline, applied to the new programs)
                maxn = cfg.max_seq // kv_block_size
                pad_ids = jnp.zeros((maxn,), jnp.int32)
                if role == "prefill":
                    k_rows, v_rows = fns.kv_handoff_export(
                        self._cache, pad_ids)
                    jax.block_until_ready(k_rows)
                    del k_rows, v_rows
                else:
                    row_shape = (maxn,) + self._cache["k"][:, 0].shape
                    zr = jnp.zeros(row_shape, self._cache["k"].dtype)
                    self._cache = fns.kv_handoff_install(
                        self._cache, pad_ids, zr, zr, np.int32(0),
                        jnp.zeros((maxn,), jnp.int32), np.int32(0))
                    jax.block_until_ready(self._cache["k"])
            # perf observatory: mirror process-wide program compile
            # events into this deployment's program-keyed recompile
            # counter (decode/sharded-decode shape churn visible, not
            # just prefill buckets); weak subscription — a retired
            # engine drops out of the registry automatically
            from ray_tpu._private.device_stats import get_registry

            get_registry().subscribe(
                self._telemetry.record_program_compile)
            # recompile-storm trips journal into the flight recorder
            # and (with an SLOConfig) trigger postmortem dumps
            get_registry().subscribe_storms(
                self._telemetry.record_storm)
            if slo is not None:
                from ray_tpu.serve.slo import SLOTracker

                self._telemetry.slo = SLOTracker(
                    slo, self._telemetry,
                    recorder=self._telemetry.flightrec)

        def _sampler_for(self, sp):
            """Per-SamplingParams jitted full-batch sampler for
            requests overriding the engine default.  Cached per sp —
            the override path costs one extra dispatch per step, never
            a recompile storm."""
            fn = self._samplers.get(sp)
            if fn is None:
                import jax

                from ray_tpu.models.decode_common import (
                    make_vocab_tail_mask, sample_token)

                tail = make_vocab_tail_mask(self.cfg)
                fn = jax.jit(lambda lg, kk: sample_token(
                    lg, kk, sp.temperature, tail, sp.top_k, sp.top_p))
                self._samplers[sp] = fn
            return fn

        def _hit_stop(self, out) -> bool:
            """Host-side stop matching over the GENERATED tokens (the
            prompt can never trigger a stop)."""
            if eos_id is not None and out[-1] == eos_id:
                return True
            for s in stop_seqs:
                if len(out) >= len(s) and tuple(out[-len(s):]) == s:
                    return True
            return False

        def _draft_admit(self, slot, arr) -> None:
            """Mirror a just-admitted request into the draft cache
            pool: full-prompt draft prefill (even when the paged
            target reused a resident prefix — the dense draft pool has
            no prefix cache) + row admit.  The draft's own first-token
            sample is discarded; the TARGET's prefill token is
            authoritative and becomes `cur`."""
            if self._draft_params is None:
                if spec_decode is not None:
                    self._spec_rej[slot] = 0
                return
            import jax
            import jax.numpy as jnp

            n = int(arr.shape[0])
            t_pad = -(-n // prefill_bucket) * prefill_bucket
            t_pad = max(n, min(t_pad, self._draft_cfg.max_seq
                               - max_new_tokens))
            padded = np.zeros((1, t_pad), np.int32)
            padded[0, t_pad - n:] = arr
            self._rng, k = jax.random.split(self._rng)
            _tok, row = self._fns.draft_prefill(
                self._draft_params, jnp.asarray(padded),
                jnp.asarray([n], jnp.int32), k)
            self._draft_cache = self._admit(self._draft_cache, row,
                                            slot)
            self._spec_rej[slot] = 0

        def _admit_pending(self) -> None:
            """Prefill queued requests into free slots (one batched
            prefill dispatch each; K/V rows land in the pool cache).
            Paged layout: blocks are matched/allocated through the
            pager first — a request the pool cannot hold yet goes back
            to the queue HEAD and admission pauses until a retirement
            frees blocks."""
            import jax
            import jax.numpy as jnp

            while len(self._queue):
                free = [i for i, s in enumerate(self._slots)
                        if s is None]
                if not free:
                    return
                ((arr, rec, sp), fut), = self._queue.pop(1)
                if isinstance(arr, HandoffCursor):
                    # disaggregated handoff package from a prefill
                    # replica — block-table splice, never a prefill
                    if not self._admit_one_handoff(arr, rec, fut,
                                                   free[0]):
                        return      # pool exhausted — retry later
                    continue
                n = int(arr.shape[0])
                if n == 0 or n + max_new_tokens > self.cfg.max_seq:
                    self._telemetry.record_reject(
                        rec, reason=f"prompt length {n}",
                        label="oversized")
                    if not fut.done():
                        fut.set_exception(ValueError(
                            f"prompt length {n} invalid for "
                            f"max_seq={self.cfg.max_seq} with "
                            f"max_new_tokens={max_new_tokens}"))
                    continue
                slot = free[0]
                if self._pager is not None:
                    if not self._admit_one_paged(arr, rec, sp, fut,
                                                 slot):
                        return          # pool exhausted — retry later
                    continue
                # pad up to the bucket so the prefill program compiles
                # once per bucket; never past the decode headroom
                t_pad = -(-n // prefill_bucket) * prefill_bucket
                t_pad = max(n, min(t_pad,
                                   self.cfg.max_seq - max_new_tokens))
                self._telemetry.record_admit(rec, slot, t_pad)
                padded = np.zeros((1, t_pad), np.int32)
                padded[0, t_pad - n:] = arr
                self._rng, k = jax.random.split(self._rng)
                if sp is not None:
                    # override path: logits-returning twin + the
                    # per-sp sampler (default requests keep the fused
                    # single-dispatch program)
                    logits, row = self._fns.prefill_raw(
                        self.params, jnp.asarray(padded),
                        jnp.asarray([n], jnp.int32))
                    tok = self._sampler_for(sp)(logits, k)
                else:
                    tok, row = self._prefill(
                        self.params, jnp.asarray(padded),
                        jnp.asarray([n], jnp.int32), k)
                # int() is the engine's existing host fence for the
                # prefill result; the timestamp behind it is the TTFT
                first = int(np.asarray(tok)[0])
                self._telemetry.record_first_token(rec)
                if max_new_tokens <= 1 or self._hit_stop([first]):
                    self._telemetry.record_finish(rec, n_tokens=1)
                    if not fut.done():
                        fut.set_result(np.concatenate(
                            [arr, np.asarray([first], np.int32)]))
                    continue
                self._cache = self._admit(self._cache, row, slot)
                self._cur[slot] = first
                self._slots[slot] = {"prompt": arr, "out": [first],
                                     "fut": fut, "rec": rec, "sp": sp}
                self._draft_admit(slot, arr)

        def _admit_one_paged(self, arr, rec, sp, fut, slot) -> bool:
            """Admit one request through the block pager: match the
            longest resident prompt prefix, allocate the remaining
            blocks up front (decode never allocates), COW-fork the
            write-boundary block if it is shared, then prefill only
            the unmatched tail.  Returns False when the pool cannot
            hold the request yet (request requeued at the head)."""
            import jax
            import jax.numpy as jnp

            import time as _time

            pager = self._pager
            n = int(arr.shape[0])
            tokens = arr.tolist()
            ctx = rec.get("ctx")
            pager.set_request(rec["id"],
                              ctx.trace_id if ctx is not None else None,
                              tenant=rec.get("tenant"))
            t_kv0 = _time.perf_counter()
            ev0 = pager.evictions
            # spec decode: reserve k blocks' worth of verify-overshoot
            # headroom so rejected draft K/V writes land in blocks this
            # row owns, never one the pager re-hands out
            need = pager.blocks_needed(
                n, max_new_tokens,
                headroom=spec_decode.k if spec_decode is not None
                else 0)
            prefix_len, matched = pager.match_prefix(tokens)
            alloc = pager.allocate(need - len(matched))
            if alloc is None:
                pager.release(matched)
                pager.set_request(None)
                self._telemetry.record_requeue(
                    rec, need=need, reason="pool_exhausted")
                self._queue.push_front((arr, rec, sp), fut)
                return False
            blocks = matched + alloc
            # tiered host-RAM KV cache: second-chance lookup — full
            # blocks the HBM prefix match missed may survive in the
            # host tier.  Restore each hit into a freshly-allocated
            # block with one H2D install, then bump prefix_len so the
            # tail prefill skips those tokens exactly as it does for
            # HBM-resident prefixes (content-addressed keys make the
            # restored rows the rows re-prefill would have written, so
            # outputs stay bit-identical to the dense oracle).  Probed
            # only after allocation succeeds — a requeued admission
            # must not double-count tier probes.
            pairs = pager.tier_lookup(tokens, len(matched))
            if pairs:
                t_f0 = _time.perf_counter()
                # one padded dispatch for the whole chain (the
                # program's shape is fixed at maxn, pre-compiled at
                # init).  The id/stack staging buffers persist across
                # restores: pad entries target the null write sink
                # (block 0), whose content is garbage by contract, so
                # stale rows left from an earlier restore need no
                # re-zeroing.
                ids, ek, ev = self._tier_stage
                ids[:] = 0
                ids[:len(pairs)] = alloc[:len(pairs)]
                for i, (_, e) in enumerate(pairs):
                    ek[i] = e["k"]
                    ev[i] = e["v"]
                self._cache = self._fns.install_blocks(
                    self._cache, jnp.asarray(ids), jnp.asarray(ek),
                    jnp.asarray(ev))
                # fence so the h2d bucket times the transfer, not the
                # dispatch (the trainwatch h2d discipline)
                jax.block_until_ready(self._cache["k"])
                t_f1 = _time.perf_counter()
                pager.tier.note_h2d(t_f1 - t_f0)
                restored = pager.note_tier_restore(pairs, alloc)
                prefix_len += restored
                self._telemetry.record_kv_fetch(
                    rec, t_f0, t_f1, blocks=len(pairs),
                    tokens=restored,
                    bytes=sum(int(e["bytes"]) for _, e in pairs))
            wb = prefix_len // kv_block_size
            if wb < len(matched):
                # the tail's first write lands inside a matched block
                try:
                    new_blk, src = pager.ensure_private(blocks[wb])
                except MemoryError:
                    pager.release(blocks)
                    pager.set_request(None)
                    self._telemetry.record_requeue(
                        rec, need=need, reason="cow_exhausted")
                    self._queue.push_front((arr, rec, sp), fut)
                    return False
                if src is not None:
                    blocks[wb] = new_blk
                    self._cache = self._copy_block(
                        self._cache, np.int32(src), np.int32(new_blk))
                    self._telemetry.record_cow()
            pager.set_request(None)
            self._telemetry.record_kv_reserve(
                rec, t_kv0, _time.perf_counter(), blocks=len(blocks),
                hit_blocks=len(matched),
                evicted=pager.evictions - ev0)
            # tier-restored blocks count as reuse hits (served from
            # cache, just a slower tier), mirroring the pager's own
            # hit/miss accounting in note_tier_restore
            reused = len(matched) + len(pairs)
            self._telemetry.record_prefix_reuse(
                reused, pager.blocks_needed(n, 0) - reused)
            n_tail = n - prefix_len
            row_bt = np.zeros((self.cfg.max_seq // kv_block_size,),
                              np.int32)
            row_bt[:len(blocks)] = blocks
            if prefill_chunk_tokens is not None \
                    and n_tail > prefill_chunk_tokens:
                # chunked streaming admission: blocks are reserved
                # (and COW-forked) exactly as the one-shot path above,
                # but the prefill itself runs as block-sized chunks
                # from the engine loop (_prefill_chunk_step) so decode
                # waves interleave with a long prompt instead of
                # stalling behind one giant dispatch
                t_pad = -(-prefill_chunk_tokens // prefill_bucket) \
                    * prefill_bucket
                self._telemetry.record_admit(rec, slot, t_pad)
                self._slots[slot] = {
                    "state": "prefill", "prompt": arr, "out": [],
                    "fut": fut, "rec": rec, "sp": sp, "blocks": blocks,
                    "row_bt": row_bt,
                    "cursor": ChunkCursor(
                        total=n, chunk_tokens=prefill_chunk_tokens,
                        filled=prefix_len)}
                if spec_decode is not None:
                    self._spec_rej[slot] = 0
                self._telemetry.record_kv_stats(pager.stats())
                return True
            t_pad = -(-n_tail // prefill_bucket) * prefill_bucket
            t_pad = max(n_tail, min(t_pad, self.cfg.max_seq))
            self._telemetry.record_admit(rec, slot, t_pad)
            tail_toks = np.zeros((1, t_pad), np.int32)
            tail_toks[0, t_pad - n_tail:] = arr[prefix_len:]
            self._rng, k = jax.random.split(self._rng)
            if sp is not None:
                logits, self._cache = self._fns.paged_prefill_raw(
                    self.params, self._cache, jnp.asarray(tail_toks),
                    jnp.asarray(row_bt), np.int32(prefix_len),
                    np.int32(n_tail), np.int32(slot))
                tok = self._sampler_for(sp)(logits, k)
            else:
                tok, self._cache = self._paged_prefill(
                    self.params, self._cache, jnp.asarray(tail_toks),
                    jnp.asarray(row_bt), np.int32(prefix_len),
                    np.int32(n_tail), np.int32(slot), k)
            # int() is the engine's existing host fence for the
            # prefill result; the timestamp behind it is the TTFT
            first = int(np.asarray(tok)[0])
            self._telemetry.record_first_token(rec)
            # the prompt's full blocks now hold exactly its K/V —
            # index them so later prompts can skip this work.
            # Re-bracketed in the request context: registration is
            # where kvscope books re-prefill waste (a previously
            # evicted key coming back), and the booking must carry
            # this request's tenant/trace
            pager.set_request(rec["id"],
                              ctx.trace_id if ctx is not None else None,
                              tenant=rec.get("tenant"))
            waste = pager.register_prefix(tokens, blocks)
            pager.set_request(None)
            if waste:
                self._telemetry.note_kv_waste(rec, waste)
            if max_new_tokens <= 1 or self._hit_stop([first]):
                self._telemetry.record_finish(rec, n_tokens=1)
                if not fut.done():
                    fut.set_result(np.concatenate(
                        [arr, np.asarray([first], np.int32)]))
                self._retire_paged_row(slot, blocks)
                return True
            if role == "prefill":
                # disaggregated serving: the request's decode belongs
                # to a decode replica — export the filled block rows,
                # resolve the future with a HandoffCursor package, and
                # free this replica's row/blocks (registered full
                # blocks park in the LRU, keeping the prefix warm)
                self._handoff_out(slot, arr, rec, sp, fut, blocks,
                                  first)
                return True
            self._cur[slot] = first
            self._slots[slot] = {"prompt": arr, "out": [first],
                                 "fut": fut, "rec": rec, "sp": sp,
                                 "blocks": blocks}
            self._draft_admit(slot, arr)
            self._telemetry.record_kv_stats(pager.stats())
            return True

        def _tier_save(self, blk) -> tuple:
            """The pager's block-saver callback (serve/kv_tier.py):
            D2H gather of one pool block's K/V rows at eviction time.
            One jitted save_block dispatch slices K and V together and
            device_get pulls both to host in one transfer pair
            (gathering shards on a mesh-sharded cache, so the stored
            copy is always the full replicated block; the jitted
            install_blocks program re-distributes it under the cache's
            shardings on restore).  The copy is timed into the tier's
            d2h bucket trainwatch-style — the tier itself never reads
            a clock."""
            import time as _time

            import jax

            t0 = _time.perf_counter()
            k_rows, v_rows = jax.device_get(
                self._fns.save_block(self._cache, np.int32(blk)))
            self._pager.tier.note_d2h(_time.perf_counter() - t0)
            return k_rows, v_rows

        def _retire_paged_row(self, slot, blocks) -> None:
            """Free a finished/errored row's blocks.  The row's table
            is pointed at the null block FIRST: an idle row's decode
            step still scatter-writes (masked garbage), which must
            never land in a block the pager may re-hand out."""
            self._cache = self._clear_row(self._cache, np.int32(slot))
            self._pager.release(blocks)
            self._telemetry.record_kv_stats(self._pager.stats())

        def _handoff_out(self, slot, arr, rec, sp, fut, blocks,
                         first) -> None:
            """Prefill-role park: export the request's filled block
            rows and resolve its future with a `HandoffCursor` package
            the router forwards to a decode replica.  The fast path
            keeps the rows on device (same-process handoff is a
            device-side gather the install splices straight back); the
            staged path pulls them to host so the package can cross a
            process/host boundary as a D2H→H2D hop.  Either way the
            rows are the EXACT bytes prefill wrote — the decode-side
            splice re-creates the monolithic engine's post-prefill
            cache state bit-for-bit.  This replica's row and blocks
            are freed immediately; registered full blocks park in the
            pager LRU, so the prefix index stays warm for
            prefix-affinity admissions."""
            import time as _time

            import jax
            import jax.numpy as jnp

            n = int(arr.shape[0])
            n_blk = -(-n // kv_block_size)
            ids = self._handoff_ids
            ids[:] = 0
            ids[:n_blk] = blocks[:n_blk]
            t0 = _time.perf_counter()
            k_rows, v_rows = self._fns.kv_handoff_export(
                self._cache, jnp.asarray(ids))
            if handoff_staged:
                k_rows, v_rows = jax.device_get((k_rows, v_rows))
                path = "staged"
            else:
                # fence so the export window is real device time, not
                # just the dispatch (the tier d2h discipline)
                jax.block_until_ready(k_rows)
                path = "fast"
            t1 = _time.perf_counter()
            nbytes = self._pager.bytes_per_block * n_blk
            # the decode replica's telemetry record is pre-populated
            # from this meta so the merged request anatomy keeps ONE
            # unbroken clock: router enqueue → prefill → handoff →
            # decode, with the critical path still summing to e2e
            meta = {
                "prompt_len": n,
                "enqueue": rec["enqueue"],
                "engine_enqueue": rec["engine_enqueue"],
                "admit": rec["admit"],
                "first_token": rec["first_token"],
                "bucket": rec["bucket"],
                "requeues": rec.get("requeues", 0),
                "requeue_ts": rec.get("requeue_ts"),
                "kv_reserve": rec.get("kv_reserve"),
                "kv_fetch": rec.get("kv_fetch"),
                "prefill_chunks": rec.get("prefill_chunks"),
                "tenant": rec.get("tenant"),
                "ctx": rec.get("ctx"),
            }
            pkg = HandoffCursor(
                prompt=arr, first_token=int(first), n_tokens=n,
                n_blocks=n_blk, k_rows=k_rows, v_rows=v_rows,
                nbytes=nbytes, path=path, t_export0=t0, t_export1=t1,
                meta=meta, sampling=sp)
            self._telemetry.record_handoff_out(
                rec, blocks=n_blk, nbytes=nbytes, path=path)
            self._retire_paged_row(slot, blocks)
            if not fut.done():
                fut.set_result(pkg)

        def _admit_one_handoff(self, pkg, rec, fut, slot) -> bool:
            """Decode-role admission of a prefilled handoff package:
            allocate a fresh block chain, splice the exported rows +
            table/pos/start into this replica's pool in one donated
            dispatch, and enter decode at the package's first token.
            `pos = prompt_len`, `start = 0` — exactly the state
            `paged_prefill` leaves — so the first decode step here is
            bit-identical to the monolithic engine by construction.
            Returns False when the pool cannot hold the chain yet
            (package requeued at the head, admission pauses)."""
            import time as _time

            import jax
            import jax.numpy as jnp

            pager = self._pager
            arr = pkg.prompt
            n = int(pkg.n_tokens)
            ctx = rec.get("ctx")
            pager.set_request(rec["id"],
                              ctx.trace_id if ctx is not None else None,
                              tenant=rec.get("tenant"))
            need = pager.blocks_needed(
                n, max_new_tokens,
                headroom=spec_decode.k if spec_decode is not None
                else 0)
            alloc = pager.allocate(need)
            if alloc is None:
                pager.set_request(None)
                self._telemetry.record_requeue(
                    rec, need=need, reason="handoff_pool_exhausted")
                self._queue.push_front((pkg, rec, pkg.sampling), fut)
                return False
            n_blk = int(pkg.n_blocks)
            ids = self._handoff_ids
            ids[:] = 0
            ids[:n_blk] = alloc[:n_blk]
            row_bt = np.zeros((self.cfg.max_seq // kv_block_size,),
                              np.int32)
            row_bt[:need] = alloc
            self._cache = self._fns.kv_handoff_install(
                self._cache, jnp.asarray(ids),
                jnp.asarray(pkg.k_rows), jnp.asarray(pkg.v_rows),
                np.int32(slot), jnp.asarray(row_bt), np.int32(n))
            # fence: the handoff window must time the transfer+splice,
            # not the dispatch (the tier-restore h2d discipline)
            jax.block_until_ready(self._cache["k"])
            t_done = _time.perf_counter()
            pkg.installed = True
            # index the imported full blocks so later prompts sharing
            # the prefix hit HERE — the router's prefix-affinity stage
            # then skips prefill entirely for them
            pager.note_handoff_import(arr.tolist(), alloc)
            pager.set_request(None)
            self._telemetry.record_kv_handoff(
                rec, pkg.t_export0, t_done, blocks=n_blk,
                nbytes=int(pkg.nbytes), path=pkg.path)
            self._telemetry.record_admit_handoff(rec, slot)
            first = int(pkg.first_token)
            self._cur[slot] = first
            self._slots[slot] = {"prompt": arr, "out": [first],
                                 "fut": fut, "rec": rec,
                                 "sp": pkg.sampling, "blocks": alloc}
            self._draft_admit(slot, arr)
            self._telemetry.record_kv_stats(pager.stats())
            return True

        def _prefill_chunk_step(self, candidates) -> None:
            """Run AT MOST ONE chunk of pending prefill — the engine
            loop alternates `decode wave → one chunk → decode wave`.
            Fairness is round-robin over the slots mid-prefill
            (`candidates`), so one 32k prompt cannot consume
            consecutive chunk windows while another long prompt waits.

            Each chunk is the existing paged_prefill program with
            prefix_len = tokens already filled — prior chunks are
            literally resident prefix blocks — so the chunked result
            is bit-identical to one-shot prefill by construction, and
            the program compiles once per prefill_bucket-padded chunk
            shape.  Between chunks the row is PARKED (null block
            table): decode waves scatter-write masked garbage into
            every row at its pos, and those writes must land in the
            null block, never in this row's half-filled real blocks;
            the next chunk re-installs row_bt/pos/start absolutely."""
            import time as _time

            import jax
            import jax.numpy as jnp

            # next candidate strictly after the cursor, cyclically
            i = min(candidates,
                    key=lambda s: ((s - self._chunk_rr) % max_slots)
                    or max_slots)
            self._chunk_rr = i
            st = self._slots[i]
            arr = st["prompt"]
            n = int(arr.shape[0])
            cur = st["cursor"]
            filled = cur.filled
            c = cur.next_chunk()
            last = filled + c >= n
            t_pad = -(-c // prefill_bucket) * prefill_bucket
            t_pad = max(c, min(t_pad, self.cfg.max_seq))
            chunk_toks = np.zeros((1, t_pad), np.int32)
            chunk_toks[0, t_pad - c:] = arr[filled:filled + c]
            t0 = _time.perf_counter()
            if last:
                self._rng, k = jax.random.split(self._rng)
            else:
                # intermediate chunks discard their sample, so the
                # fused program runs under a constant key — the
                # engine RNG stream stays identical to a one-shot
                # admission (exactly one split, at the final chunk)
                k = self._dummy_key
            first = None
            if st["sp"] is not None:
                logits, self._cache = self._fns.paged_prefill_raw(
                    self.params, self._cache, jnp.asarray(chunk_toks),
                    jnp.asarray(st["row_bt"]), np.int32(filled),
                    np.int32(c), np.int32(i))
                if last:
                    tok = self._sampler_for(st["sp"])(logits, k)
                    first = int(np.asarray(tok)[0])
                else:
                    # host fence so the chunk window is real device
                    # time, mirroring the one-shot path's int()
                    np.asarray(logits[0, 0])
            else:
                tok, self._cache = self._paged_prefill(
                    self.params, self._cache, jnp.asarray(chunk_toks),
                    jnp.asarray(st["row_bt"]), np.int32(filled),
                    np.int32(c), np.int32(i), k)
                # the chunk's host fence (the one-shot path's int());
                # intermediate chunks discard the value
                first = int(np.asarray(tok)[0])
            t1 = _time.perf_counter()
            cur.advance(c)
            self._telemetry.record_prefill_chunk(
                st["rec"], t0, t1, tokens=c, bucket=t_pad, last=last)
            # journal the fill under this request's id/trace, same
            # bracketing idiom as the admission reservation window
            ctx = st["rec"].get("ctx")
            self._pager.set_request(
                st["rec"]["id"],
                ctx.trace_id if ctx is not None else None,
                tenant=st["rec"].get("tenant"))
            self._pager.note_fill(c, partial=not last)
            self._pager.set_request(None)
            if not last:
                self._cache = self._clear_row(self._cache, np.int32(i))
                return
            rec, fut, blocks = st["rec"], st["fut"], st["blocks"]
            self._telemetry.record_first_token(rec)
            # registration under the request context: kvscope books
            # re-prefill waste (previously-evicted keys returning)
            # against this request's tenant
            self._pager.set_request(
                rec["id"], ctx.trace_id if ctx is not None else None,
                tenant=rec.get("tenant"))
            waste = self._pager.register_prefix(arr.tolist(), blocks)
            self._pager.set_request(None)
            if waste:
                self._telemetry.note_kv_waste(rec, waste)
            if max_new_tokens <= 1 or self._hit_stop([first]):
                self._telemetry.record_finish(rec, n_tokens=1)
                if not fut.done():
                    fut.set_result(np.concatenate(
                        [arr, np.asarray([first], np.int32)]))
                self._slots[i] = None
                self._retire_paged_row(i, blocks)
                return
            if role == "prefill":
                # chunked long prompts hand off too: the last chunk's
                # filled rows move wholesale, so a 32k prompt never
                # decodes on the prefill replica it streamed through
                self._slots[i] = None
                self._handoff_out(i, arr, rec, st["sp"], fut, blocks,
                                  first)
                return
            self._cur[i] = first
            st["state"] = "decode"
            st["out"] = [first]
            self._draft_admit(i, arr)
            self._telemetry.record_kv_stats(self._pager.stats())

        def _finish_slot(self, i, st) -> None:
            """Retire a finished slot NOW — the freed slot (and its
            paged blocks) is admissible in the same engine wave."""
            self._telemetry.record_finish(st["rec"],
                                          n_tokens=len(st["out"]))
            if not st["fut"].done():
                # st["out"] is a python int list — no device fetch
                tail = np.asarray(st["out"], np.int32)
                st["fut"].set_result(np.concatenate(
                    [st["prompt"], tail]))
            self._slots[i] = None           # slot freed NOW
            if self._pager is not None:
                self._retire_paged_row(i, st["blocks"])

        def _mixed_step(self, key):
            """One decode step when any active slot overrides the
            engine SamplingParams: the logits-twin program once, then
            one jitted sampler dispatch per DISTINCT SamplingParams
            among active slots, rows gathered host-side."""
            import jax
            import jax.numpy as jnp

            logits, self._cache = self._fns.pool_logits(
                self.params, self._cache, jnp.asarray(self._cur))
            toks = np.zeros((max_slots,), np.int32)
            groups: Dict[Any, list] = {}
            for i, st in enumerate(self._slots):
                if st is None or st.get("state") == "prefill":
                    continue
                groups.setdefault(st["sp"] or self._default_sp,
                                  []).append(i)
            for sp, rows in groups.items():
                key, kk = jax.random.split(key)
                full = np.asarray(self._sampler_for(sp)(logits, kk))
                for r in rows:
                    toks[r] = full[r]
            return toks

        def _spec_round(self) -> int:
            """One speculative round over the whole slot pool: draft
            proposes k tokens per row, ONE target verify dispatch
            checks all k+1 positions, accepted tokens are emitted and
            the caches advance by exactly the kept count.  Returns the
            number of tokens emitted (for step telemetry)."""
            import time as _time

            import jax
            import jax.numpy as jnp

            from ray_tpu.models.decode_common import ngram_propose

            t_round = _time.perf_counter()
            kd = spec_decode.k
            qprobs = None
            if self._draft_params is not None:
                self._rng, dk = jax.random.split(self._rng)
                if self._spec_sampled:
                    drafts, qprobs, self._draft_cache = \
                        self._fns.draft_propose(
                            self._draft_params, self._draft_cache,
                            jnp.asarray(self._cur),
                            jnp.asarray(self._spec_rej), dk)
                else:
                    drafts, self._draft_cache = \
                        self._fns.draft_propose(
                            self._draft_params, self._draft_cache,
                            jnp.asarray(self._cur),
                            jnp.asarray(self._spec_rej), dk)
                drafts = np.asarray(drafts)
            else:
                # host-side n-gram draft over each request's own
                # history: zero extra weights, zero extra dispatches
                drafts = np.zeros((max_slots, kd), np.int32)
                for i, st in enumerate(self._slots):
                    if st is None or st.get("state") == "prefill":
                        continue
                    drafts[i] = ngram_propose(
                        st["prompt"].tolist() + st["out"], kd,
                        order=spec_decode.ngram_order)
            block = np.concatenate([self._cur[:, None], drafts],
                                   axis=1)
            self._rng, vk = jax.random.split(self._rng)
            if self._spec_sampled:
                out_toks, n_acc, self._cache = self._fns.spec_verify(
                    self.params, self._cache, jnp.asarray(block), vk,
                    qprobs)
            else:
                out_toks, n_acc, self._cache = self._fns.spec_verify(
                    self.params, self._cache, jnp.asarray(block), vk)
            # the round's one deliberate host fence (same role as the
            # plain engine's np.asarray(toks))
            out_toks = np.asarray(out_toks)
            n_acc = np.asarray(n_acc)
            t_done = _time.perf_counter()
            round_dur = t_done - t_round
            total = 0
            for i, st in enumerate(self._slots):
                if st is None or st.get("state") == "prefill":
                    # mid-prefill rows are parked (null block table):
                    # the pool-wide verify dispatch covers them but
                    # their outputs are discarded
                    continue
                n = int(n_acc[i])
                self._telemetry.record_spec(st["rec"], proposed=kd,
                                            accepted=n,
                                            dur_s=round_dur)
                finished = False
                emitted = 0
                for t in out_toks[i, :n + 1]:
                    st["out"].append(int(t))
                    total += 1
                    emitted += 1
                    if len(st["out"]) >= max_new_tokens \
                            or self._hit_stop(st["out"]):
                        finished = True
                        break
                # one dispatch emitted `emitted` tokens for this row —
                # they share the round-end timestamp in the ITL trail
                self._telemetry.record_token(st["rec"], n=emitted,
                                             now=t_done)
                # the correction token is always the row's new `cur`
                # (it has no K/V yet — exactly a fresh sampled token)
                self._cur[i] = out_toks[i, n]
                self._spec_rej[i] = 0 if finished else kd - n
                if finished:
                    self._finish_slot(i, st)
            return total

        async def _engine(self):
            """The scheduler loop: admit → one pooled decode step (or
            one speculative draft+verify round) over the decoding
            slots → retire finished slots → at most ONE chunk of
            pending chunked prefill → yield (so new requests enqueue
            mid-generation).  The decode-wave/chunk alternation is the
            chunked-prefill scheduler: a long prompt costs the other
            slots one chunk window per wave, never a full prefill."""
            import asyncio
            import time as _time

            import jax
            import jax.numpy as jnp

            while True:
                try:
                    if self._chaos is not None and \
                            self._chaos.frozen(self._replica_label):
                        # chaos freeze: poll without processing and —
                        # crucially — without heartbeating, exactly
                        # what a wedged host looks like to healthwatch
                        await asyncio.sleep(self._chaos.freeze_poll_s)
                        continue
                    if self._health is not None:
                        # one liveness stamp per wave (a dict store)
                        self._health.heartbeat(self._replica_label)
                    self._admit_pending()
                    prefilling = [
                        i for i, s in enumerate(self._slots)
                        if s is not None
                        and s.get("state") == "prefill"]
                    n_active = sum(s is not None for s in self._slots)
                    if not n_active:
                        self._wake.clear()
                        if not len(self._queue):
                            if self._health is not None:
                                # parked-idle is not a failure: the
                                # probe skips idle replicas until the
                                # next heartbeat re-arms the clock
                                self._health.note_idle(
                                    self._replica_label)
                            await self._wake.wait()
                        continue
                    n_decode = n_active - len(prefilling)
                    if self._chaos is not None and n_decode:
                        delay_s = self._chaos.token_delay_s(
                            self._replica_label)
                        if delay_s > 0:
                            # chaos token delay: the loop still
                            # heartbeats but its requests go token-
                            # silent — only the stall sweep sees this
                            await asyncio.sleep(delay_s)
                    # step walltime: dispatch + the np.asarray host
                    # fence the engine already performs — perf_counter
                    # pairs only, no extra device sync
                    if n_decode and spec_decode is not None:
                        t_step = _time.perf_counter()
                        n_tokens = self._spec_round()
                        self._telemetry.record_step(
                            n_decode,
                            _time.perf_counter() - t_step,
                            n_tokens=n_tokens)
                    elif n_decode:
                        t_step = _time.perf_counter()
                        self._rng, k = jax.random.split(self._rng)
                        if any(st is not None
                               and st.get("state") != "prefill"
                               and st["sp"] is not None
                               for st in self._slots):
                            toks = self._mixed_step(k)
                        else:
                            toks, self._cache = self._pool_step(
                                self.params, self._cache,
                                jnp.asarray(self._cur), k)
                            # graftcheck: disable=blocking-call-in-async(the per-step host fence)
                            toks = np.asarray(toks)
                        t_wave = _time.perf_counter()
                        self._telemetry.record_step(
                            n_decode, t_wave - t_step, now=t_wave)
                        for i, st in enumerate(self._slots):
                            if st is None \
                                    or st.get("state") == "prefill":
                                continue
                            st["out"].append(int(toks[i]))
                            self._telemetry.record_token(st["rec"],
                                                         now=t_wave)
                            self._cur[i] = toks[i]
                            if len(st["out"]) >= max_new_tokens \
                                    or self._hit_stop(st["out"]):
                                self._finish_slot(i, st)
                    if self._telemetry.slo is not None:
                        # throttled burn-rate watchdog: breach / storm
                        # transitions postmortem-dump the flight record
                        self._telemetry.slo.check()
                    if self._health is not None:
                        # throttled liveness sweep: healthy replicas'
                        # waves age their peers' heartbeats even while
                        # the router is quiet
                        self._health.maybe_probe()
                    if self._pager is not None:
                        # kvscope occupancy ring: one pool snapshot
                        # per wave (host counters only, no device
                        # sync) — the timeline a postmortem replays
                        self._pager.sample_occupancy()
                    if prefilling:
                        self._prefill_chunk_step(prefilling)
                except Exception as e:  # noqa: BLE001 - fail loudly
                    # crash postmortem: the journal around the failure
                    # is exactly what the flight recorder exists for —
                    # dump BEFORE unwinding mutates engine state
                    self._telemetry.flightrec.record(
                        "engine_crash", error=repr(e)[:200])
                    try:
                        self._telemetry.flightrec.dump(
                            reason="engine_crash",
                            context={"error": repr(e)[:500]})
                    except Exception:  # noqa: BLE001 - dump best-effort
                        pass
                    for i, st in enumerate(self._slots):
                        if st is not None:
                            self._telemetry.record_error(
                                st["rec"], error=repr(e))
                            if not st["fut"].done():
                                st["fut"].set_exception(e)
                            if self._pager is not None \
                                    and "blocks" in st:
                                self._pager.release(st["blocks"])
                        self._slots[i] = None
                    for (arr, rec, _sp), fut in self._queue.pop(
                            len(self._queue)):
                        self._telemetry.record_error(rec, error=repr(e))
                        if not fut.done():
                            fut.set_exception(e)
                # yield the loop so callers can enqueue mid-flight
                await asyncio.sleep(0)

        async def _call_continuous(self, prompt, sampling=None, *,
                                   tenant=None, enqueue_ts=None,
                                   trace=None):
            """`tenant` / `enqueue_ts` / `trace` are the fleet-router
            hooks (serve/router.py): the router backdates `enqueue_ts`
            to the instant the request entered ITS queue, so this
            engine's telemetry charges router wait to the request's
            TTFT/e2e series, `tenant` tags the record for per-class
            SLO slicing, and `trace` is the tracebus TraceContext born
            at router submit (a fresh engine-origin context is minted
            when absent).  Direct callers omit all three."""
            import asyncio

            sp = None
            if sampling is not None:
                if not isinstance(sampling, SamplingParams):
                    raise ValueError(
                        "sampling must be a SamplingParams, got "
                        f"{type(sampling).__name__}")
                if spec_decode is not None:
                    raise ValueError(
                        "per-request sampling overrides are not "
                        "supported with spec_decode (the verify "
                        "program bakes in ONE sampling config; build "
                        "a separate deployment per config)")
                if sampling != self._default_sp:
                    sp = sampling
            if self._wake is None:
                self._wake = asyncio.Event()
            if self._engine_task is None or self._engine_task.done():
                self._engine_task = asyncio.get_running_loop(
                ).create_task(self._engine())
            # host-side prompt normalization (python ints, no device fetch)
            # graftcheck: disable=blocking-call-in-async(host-side int normalization)
            arr = np.asarray(prompt, np.int32).reshape(-1)
            if admission_policy is not None:
                # the control loop: telemetry percentiles feed the
                # shed decision BEFORE the request costs the engine
                # anything.  The HBM-headroom gate needs a FRESH
                # ledger (engine_stats serves the last composed one):
                # refresh only when that gate is armed — the device
                # allocator query stays off the default admit path
                if getattr(admission_policy, "min_headroom_bytes",
                           None) is not None \
                        and getattr(self, "_pager", None) is not None:
                    self._telemetry.record_kv_scope(
                        self._compose_kv_scope())
                shed = admission_policy.decide(
                    self._telemetry.engine_stats(), len(self._queue))
                if shed is not None:
                    rec = self._telemetry.record_enqueue(
                        int(arr.shape[0]), now=enqueue_ts,
                        tenant=tenant, ctx=trace)
                    self._telemetry.record_reject(
                        rec, reason=f"load shed: {shed}",
                        label=f"shed_{shed}")
                    raise OverloadedError(
                        f"request shed ({shed}): engine over SLO "
                        f"with {len(self._queue)} queued")
            rec = self._telemetry.record_enqueue(
                int(arr.shape[0]), now=enqueue_ts, tenant=tenant,
                ctx=trace)
            fut = self._queue.put((arr, rec, sp))
            self._wake.set()
            return await fut

        async def admit_prefilled(self, pkg):
            """Second-stage entry point for disaggregated serving: the
            fleet router forwards a prefill replica's `HandoffCursor`
            package here.  The package's telemetry meta seeds a record
            that keeps the request's original enqueue/admit/TTFT
            clock, so the merged anatomy spans both replicas with one
            unbroken critical path.  Decode starts from the package's
            first token after the block splice — no prefill runs on
            this engine for the request."""
            import asyncio

            if role == "prefill":
                raise ValueError(
                    "admit_prefilled needs a decode-capable engine "
                    "(role='decode' or 'both'); this replica is "
                    "role='prefill'")
            if self._pager is None:
                raise ValueError(
                    "admit_prefilled requires kv_layout='paged'")
            if not isinstance(pkg, HandoffCursor):
                raise ValueError(
                    "admit_prefilled takes a HandoffCursor, got "
                    f"{type(pkg).__name__}")
            if pkg.sampling is not None and spec_decode is not None:
                raise ValueError(
                    "per-request sampling overrides are not "
                    "supported with spec_decode (the verify program "
                    "bakes in ONE sampling config)")
            if self._wake is None:
                self._wake = asyncio.Event()
            if self._engine_task is None or self._engine_task.done():
                self._engine_task = asyncio.get_running_loop(
                ).create_task(self._engine())
            rec = self._telemetry.record_enqueue_handoff(pkg.meta)
            fut = self._queue.put((pkg, rec, pkg.sampling))
            self._wake.set()
            return await fut

        def shutdown_engine(self) -> None:
            """Stop the background engine task (direct-instance
            drivers — traffic generator, bench — call this so their
            event loop can close cleanly; serve replicas die with
            their actor process and never need it)."""
            task, self._engine_task = self._engine_task, None
            if task is not None and not task.done():
                task.cancel()

        # -- telemetry surface (works for both schedulers) -----------

        def _compose_kv_scope(self):
            """The full engine_stats()["kv_scope"] block: the pager's
            occupancy/forensics half plus the unified HBM ledger
            (pool bytes + live allocator view + graftcheck's audited
            per-program peak budget → headroom_bytes per chip).  The
            budget term is cached after the first lookup — graftcheck
            import cost is paid once per deployment."""
            from ray_tpu._private.device_stats import \
                device_memory_stats
            from ray_tpu.serve.kvscope import (
                hbm_ledger, serve_program_budget_bytes)

            pager = self._pager
            block = pager.kv_scope_stats()
            budget = getattr(self, "_kvscope_budget", None)
            if budget is None:
                budget = serve_program_budget_bytes()
                self._kvscope_budget = budget
            mesh = getattr(self, "mesh", None)
            devices = (list(mesh.devices.flat)
                       if mesh is not None else None)
            pool_per_chip = (pager.bytes_per_block * pager.num_blocks
                             // pager.tensor_shards)
            block["hbm_ledger"] = hbm_ledger(
                pool_bytes_per_chip=pool_per_chip,
                device_stats=device_memory_stats(devices),
                program_budget_bytes=budget)
            return block

        def engine_stats(self):
            """p50/p95/p99 TTFT + queue wait, throughput, slot
            utilization, request counts, rejections by reason, and
            (paged layout) the live kv_cache block/prefix-hit stats —
            `handle.method("engine_stats").remote()` or GET
            /api/serve/stats."""
            pager = getattr(self, "_pager", None)
            if pager is not None:
                self._telemetry.record_kv_stats(pager.stats())
                self._telemetry.record_kv_scope(
                    self._compose_kv_scope())
                if pager.tier is not None:
                    self._telemetry.record_kv_tier(
                        pager.tier.stats())
            if self._health is not None:
                self._telemetry.record_health(
                    self._health.replica_block(self._replica_label))
            stats = self._telemetry.engine_stats()
            if admission_policy is not None:
                stats["admission_policy"] = admission_policy.describe()
            # perf observatory: compiled-cost / recompile / live-MFU
            # block for this engine's programs (process-wide registry,
            # filtered to the serve namespace)
            from ray_tpu._private.device_stats import (
                device_memory_stats, get_registry)

            mesh = getattr(self, "mesh", None)
            stats["programs"] = get_registry().snapshot(
                prefix="serve.",
                n_devices=int(mesh.size) if mesh is not None else 1)
            if mesh is not None:
                stats["mesh"] = {
                    "axes": {a: int(s)
                             for a, s in self.mesh.shape.items()
                             if int(s) > 1},
                    "n_devices": int(self.mesh.size),
                    "kv_shards": self._kv_shards(),
                    # per-chip allocator stats (stable keys; values
                    # are None on backends without memory_stats())
                    "devices": device_memory_stats(
                        list(self.mesh.devices.flat)),
                }
            return stats

        def export_timeline(self, path=None):
            """Chrome-trace engine timeline (queue lane, per-slot
            occupancy lanes, engine-step lane); writes `path` when
            given and returns the event list."""
            return self._telemetry.export_timeline(path)

        # -- tracebus surface (tools/tracebus.py collects these) -----

        def trace_records(self):
            """Tracebus request snapshots (hop timestamps, token
            trail, router spans) for every retained request."""
            return self._telemetry.trace_records()

        def request_trace(self, request_id):
            """One request's tracebus snapshot by trace id (or
            engine-local id); None when unknown to this replica —
            `handle.method("request_trace").remote(rid)` or GET
            /api/serve/trace/<rid>."""
            return self._telemetry.find_request(request_id)

        def anatomy_samples(self, tenant=None):
            """Raw latency-anatomy samples (ITL gaps, TPOT,
            critical-path components) — fleet_stats pools these
            across replicas before summarizing."""
            return self._telemetry.anatomy_samples(tenant=tenant)

        def metrics_snapshot(self):
            """This replica's serve_* metric dumps (histogram buckets
            included) straight from the process-local registry."""
            from ray_tpu.util.metrics import _registry

            return {name: dump for name, dump
                    in _registry.snapshot().items()
                    if name.startswith("serve_")}

    LLM.__call__ = (LLM._call_continuous if scheduler == "continuous"
                    else LLM._call_batch_traced)
    return deployment(name=f"llm_{family}_{preset}",
                      num_replicas=num_replicas)(LLM)
