"""Declarative serve config: applications as validated data.

Reference analog: serve/schema.py:1 (ServeApplicationSchema /
DeploymentSchema — pydantic there, plain dataclass validation here: no
new dependency) + serve/api.py:251's REST deploy path.  A config names
an import path and per-deployment overrides; ``apply`` imports the
target, overlays the overrides, and deploys through the normal
``serve.run`` machinery — the REST endpoint in the dashboard
(PUT /api/serve/applications/) feeds dicts straight into this module.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

_ALLOWED_DEPLOYMENT_KEYS = {
    "name", "num_replicas", "max_concurrent_queries",
    "ray_actor_options", "autoscaling_config", "route_prefix",
}


@dataclasses.dataclass
class DeploymentSchema:
    """Per-deployment overrides (reference: schema.py DeploymentSchema)."""

    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    route_prefix: Optional[str] = None

    @classmethod
    def parse(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        if not isinstance(d, dict):
            raise ValueError(f"deployment entry must be a dict, got "
                             f"{type(d).__name__}")
        unknown = set(d) - _ALLOWED_DEPLOYMENT_KEYS
        if unknown:
            raise ValueError(
                f"unknown deployment config keys {sorted(unknown)}; "
                f"allowed: {sorted(_ALLOWED_DEPLOYMENT_KEYS)}")
        if "name" not in d or not isinstance(d["name"], str) or not d["name"]:
            raise ValueError("every deployment entry needs a non-empty "
                             "string 'name'")
        out = cls(**d)
        if out.num_replicas is not None and (
                not isinstance(out.num_replicas, int)
                or out.num_replicas < 0):
            raise ValueError(f"{out.name}: num_replicas must be an int "
                             f">= 0, got {out.num_replicas!r}")
        if out.num_replicas == 0 and not (
                isinstance(out.autoscaling_config, dict)
                and out.autoscaling_config.get("min_replicas") == 0):
            # zero replicas with no autoscaler can never serve a request
            raise ValueError(
                f"{out.name}: num_replicas=0 requires an "
                "autoscaling_config with min_replicas=0 (scale-to-zero); "
                "a fixed zero-replica deployment can never serve")
        if out.max_concurrent_queries is not None and (
                not isinstance(out.max_concurrent_queries, int)
                or out.max_concurrent_queries < 1):
            raise ValueError(f"{out.name}: max_concurrent_queries must "
                             f"be an int >= 1")
        if out.route_prefix is not None and \
                not out.route_prefix.startswith("/"):
            raise ValueError(f"{out.name}: route_prefix must start "
                             f"with '/'")
        if out.autoscaling_config is not None:
            ac = out.autoscaling_config
            lo = ac.get("min_replicas", 1)
            hi = ac.get("max_replicas", 8)
            if lo > hi:
                raise ValueError(f"{out.name}: min_replicas {lo} > "
                                 f"max_replicas {hi}")
        return out


@dataclasses.dataclass
class ServeApplicationSchema:
    """One application: an import path + deployment overrides
    (reference: schema.py ServeApplicationSchema)."""

    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = None
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    @classmethod
    def parse(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        if not isinstance(d, dict):
            raise ValueError("application config must be a dict")
        imp = d.get("import_path")
        if not imp or not isinstance(imp, str) or ":" not in imp:
            raise ValueError(
                "import_path is required, format 'module.sub:attr' "
                f"(got {imp!r})")
        deps = [DeploymentSchema.parse(x)
                for x in d.get("deployments", [])]
        names = [x.name for x in deps]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate deployment names in config: "
                             f"{names}")
        rp = d.get("route_prefix")
        if rp is not None and not str(rp).startswith("/"):
            raise ValueError("route_prefix must start with '/'")
        return cls(import_path=imp, name=d.get("name", "default"),
                   route_prefix=rp, args=d.get("args", {}) or {},
                   deployments=deps)

    def resolve_target(self):
        """Import the bound deployment the config points at."""
        import importlib

        module, _, attr = self.import_path.partition(":")
        target = importlib.import_module(module)
        for part in attr.split("."):
            target = getattr(target, part)
        if callable(target) and not _is_deployment(target):
            target = target(**self.args)  # app builder function
        if not _is_deployment(target):
            raise ValueError(
                f"{self.import_path} resolved to {type(target).__name__},"
                f" expected a Deployment (use @serve.deployment)")
        return target


def _is_deployment(obj) -> bool:
    from ray_tpu.serve.api import Deployment

    return isinstance(obj, Deployment)


def apply(config: Dict[str, Any]):
    """Validate + deploy a declarative application config; returns the
    root DeploymentHandle.  The REST layer calls exactly this."""
    import dataclasses as dc

    from ray_tpu.serve import api

    schema = ServeApplicationSchema.parse(config)
    target = schema.resolve_target()
    overrides = {
        d.name: {k: v for k, v in dc.asdict(d).items()
                 if k != "name" and v is not None}
        for d in schema.deployments}
    return api.run(target, route_prefix=schema.route_prefix,
                   _overrides=overrides or None)


def status() -> Dict[str, Any]:
    """Shape-stable status document for the REST layer (reference:
    serve/schema.py ServeStatusSchema)."""
    from ray_tpu.serve import api

    return {"applications": api.status()}
