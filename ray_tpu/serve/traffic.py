"""Synthetic serve traffic: seeded Poisson arrivals over shared-prefix
prompt mixtures.

Serving benchmarks lie unless the offered load looks like production:
requests arrive in bursts (Poisson, not back-to-back), prompts cluster
around a few hot system-prompt prefixes (what the paged KV cache's
prefix reuse exists for), and lengths are ragged.  This module is the
single source of that workload for tests, ``bench.py --traffic`` and
``sweep_tpu.py`` traffic variants — everything is derived from one
integer seed, so a run is reproducible down to the token.

Pieces:

* :class:`TrafficSpec` — the workload knobs (rate, prefix groups,
  length distributions), a frozen dataclass so specs can be shared;
* :class:`TrafficGenerator` — expands a spec into concrete
  ``TrafficRequest`` records (arrival offset + int32 prompt array);
* :func:`drive` — fires the requests at an engine instance on their
  (optionally time-scaled) arrival schedule and measures per-request
  latency, shed count, and SLO attainment;
* :func:`run_traffic` — sync wrapper: builds the LLM deployment,
  drives it, merges ``engine_stats()`` into the report.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu._private.telemetry import summarize
from ray_tpu.serve.batching import OverloadedError

__all__ = ["TrafficSpec", "TenantSpec", "TrafficRequest",
           "TrafficGenerator", "drive", "drive_fleet", "run_traffic",
           "run_traffic_fleet"]

#: default WFQ weights by SLO class — interactive overtakes batch
#: whenever both are backlogged at the fleet router
_CLASS_WEIGHTS = {"interactive": 8.0, "batch": 1.0}


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One traffic class inside a multi-tenant mixture.

    `rate_share` is the tenant's relative share of the spec's offered
    rate (normalized over all tenants); `slo_class` picks the fleet
    router's default WFQ weight ("interactive" | "batch", overridable
    via `weight`); `prefix_groups` restricts the tenant to a subset of
    the spec's shared-prefix pool (its own "system prompts" — empty =
    the whole pool); `ttft_slo_ms` / `e2e_slo_ms` are the per-tenant
    latency targets scored by ``LLMFleet.tenant_report()``.

    `prompt_len` makes this a long-prompt tenant: its requests draw a
    fixed `prompt_len`-token user turn instead of the spec's Poisson
    tail (total prompt = shared prefix + prompt_len when the request
    extends a prefix) — the batch-floods-interactive mixture the
    chunked-prefill A/B needs.  When unset (every legacy spec) the
    draw order is untouched, so the RNG stream stays bit-identical.

    `prefix_pool` makes this a cache-churn tenant: its shared-prefix
    requests rotate round-robin through a private pool of N distinct
    `prefix_len`-token prefixes (drawn from a SEPARATE seeded stream)
    instead of the spec's `num_prefix_groups` — size N past what the
    pager's LRU pool can park and every rotation lap re-prefills
    evicted content, the reproducible thrash kvscope's re-prefill
    waste accounting is tested against.  Mutually exclusive with
    `prefix_groups`; when unset (every legacy spec) the main RNG
    stream stays bit-identical."""

    name: str
    rate_share: float = 1.0
    slo_class: str = "interactive"
    prefix_groups: tuple = ()
    ttft_slo_ms: Optional[float] = None
    e2e_slo_ms: Optional[float] = None
    objective: float = 0.95
    weight: Optional[float] = None
    prompt_len: Optional[int] = None
    prefix_pool: Optional[int] = None

    def __post_init__(self):
        if self.rate_share <= 0:
            raise ValueError(f"tenant {self.name!r}: rate_share must "
                             "be > 0")
        if self.prompt_len is not None and self.prompt_len < 1:
            raise ValueError(f"tenant {self.name!r}: prompt_len must "
                             "be >= 1 when set")
        if self.prefix_pool is not None:
            if self.prefix_pool < 1:
                raise ValueError(
                    f"tenant {self.name!r}: prefix_pool must be >= 1 "
                    "when set")
            if self.prefix_groups:
                raise ValueError(
                    f"tenant {self.name!r}: prefix_pool and "
                    "prefix_groups are mutually exclusive (a churn "
                    "tenant rotates its own private prefixes)")
        if self.slo_class not in _CLASS_WEIGHTS:
            raise ValueError(
                f"tenant {self.name!r}: slo_class must be one of "
                f"{sorted(_CLASS_WEIGHTS)}, got {self.slo_class!r}")
        object.__setattr__(self, "prefix_groups",
                           tuple(int(g) for g in self.prefix_groups))

    def to_class(self):
        """The router-side TenantClass this spec maps to."""
        from ray_tpu.serve.router import TenantClass

        return TenantClass(
            self.name,
            weight=self.weight if self.weight is not None
            else _CLASS_WEIGHTS[self.slo_class],
            ttft_ms=self.ttft_slo_ms, e2e_ms=self.e2e_slo_ms,
            objective=self.objective)


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """Knobs for one synthetic workload.  All randomness flows from
    ``seed`` through one ``np.random.RandomState``, so equal specs
    generate equal traffic on any host."""

    num_requests: int = 32
    seed: int = 0
    #: Poisson arrival rate (requests/second of *modeled* time);
    #: ``drive(time_scale=...)`` compresses it for fast tests.
    rate_rps: float = 50.0
    #: distinct shared prefixes ("system prompts") in the mixture
    num_prefix_groups: int = 4
    #: tokens per shared prefix (block-aligned values exercise full
    #: reuse; off-aligned values exercise the COW boundary)
    prefix_len: int = 32
    #: probability a request extends one of the shared prefixes
    #: (otherwise its whole prompt is unique)
    p_shared: float = 0.75
    #: request tail (user turn) length ~ 1 + Poisson(mean - 1)
    tail_len_mean: float = 8.0
    tail_len_max: int = 24
    vocab: int = 256
    #: multi-tenant mixture: each request is assigned a tenant in
    #: proportion to rate_share, drawing its shared prefix from the
    #: tenant's pool.  Empty = legacy single-class traffic (the RNG
    #: stream is then bit-identical to pre-tenant specs).
    tenants: tuple = ()

    def __post_init__(self):
        if self.num_requests < 1:
            raise ValueError("num_requests must be >= 1")
        if self.rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        if not 0.0 <= self.p_shared <= 1.0:
            raise ValueError("p_shared must be in [0, 1]")
        object.__setattr__(self, "tenants", tuple(self.tenants))
        names = [t.name for t in self.tenants]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate tenant names: {names}")
        for t in self.tenants:
            for g in t.prefix_groups:
                if not 0 <= g < self.num_prefix_groups:
                    raise ValueError(
                        f"tenant {t.name!r}: prefix group {g} out of "
                        f"range [0, {self.num_prefix_groups})")


@dataclasses.dataclass
class TrafficRequest:
    arrival_s: float          # offset from the start of the run
    prompt: np.ndarray        # int32 (len,)
    group: int                # shared-prefix group id, -1 = unique
    tenant: str = ""          # traffic class, "" = untagged


class TrafficGenerator:
    """Expands a :class:`TrafficSpec` into a concrete request list."""

    def __init__(self, spec: TrafficSpec):
        self.spec = spec
        self._rng = np.random.RandomState(spec.seed)
        # tokens drawn from [2, vocab): 0/1 stay reserved so traffic
        # never collides with pad/bos conventions in the model zoo
        self.prefixes = [
            self._rng.randint(2, spec.vocab, size=spec.prefix_len)
            .astype(np.int32)
            for _ in range(spec.num_prefix_groups)]
        # churn tenants (prefix_pool=N): each gets a private pool of N
        # prefixes from its own seeded stream, so the main RNG stream
        # above (and therefore every legacy draw) is untouched
        self.tenant_pools: Dict[str, List[np.ndarray]] = {}
        for i, t in enumerate(spec.tenants):
            if t.prefix_pool is None:
                continue
            pool_rng = np.random.RandomState(spec.seed + 7919 * (i + 1))
            self.tenant_pools[t.name] = [
                pool_rng.randint(2, spec.vocab, size=spec.prefix_len)
                .astype(np.int32) for _ in range(t.prefix_pool)]

    def requests(self) -> List[TrafficRequest]:
        spec, rng = self.spec, self._rng
        inter = rng.exponential(1.0 / spec.rate_rps,
                                size=spec.num_requests)
        arrivals = np.cumsum(inter)
        shares = None
        if spec.tenants:
            shares = np.array([t.rate_share for t in spec.tenants],
                              dtype=np.float64)
            shares = np.cumsum(shares / shares.sum())
        out: List[TrafficRequest] = []
        #: per-tenant round-robin cursor over its churn pool — a local
        #: so repeated requests() calls replay identically
        pool_rr: Dict[str, int] = {}
        for i in range(spec.num_requests):
            tenant, pool, plen, churn = "", None, None, None
            if shares is not None:
                idx = min(int(np.searchsorted(shares, rng.rand())),
                          len(spec.tenants) - 1)
                t = spec.tenants[idx]
                tenant = t.name
                pool = t.prefix_groups or None
                plen = t.prompt_len
                churn = self.tenant_pools.get(t.name)
            tail_len = 1 + min(int(rng.poisson(
                max(spec.tail_len_mean - 1.0, 0.0))),
                spec.tail_len_max - 1)
            if plen is not None:
                # long-prompt tenant: the Poisson draw above still
                # happens (keeps the stream aligned with prompt_len
                # unset), only the drawn size changes
                tail_len = plen
            tail = rng.randint(2, spec.vocab,
                               size=tail_len).astype(np.int32)
            if spec.num_prefix_groups > 0 \
                    and rng.rand() < spec.p_shared:
                if churn is not None:
                    # churn tenant: the group draw below still happens
                    # (keeps the stream aligned for co-tenants), but
                    # the prefix comes from the tenant's private pool,
                    # rotated round-robin so a bounded pager pool is
                    # forced through deterministic LRU eviction laps
                    rng.randint(spec.num_prefix_groups)
                    p_idx = pool_rr.get(tenant, 0)
                    pool_rr[tenant] = p_idx + 1
                    group = -2 - (p_idx % len(churn))
                    prompt = np.concatenate(
                        [churn[p_idx % len(churn)], tail])
                elif pool is not None:
                    group = int(pool[rng.randint(len(pool))])
                    prompt = np.concatenate([self.prefixes[group],
                                             tail])
                else:
                    group = int(rng.randint(spec.num_prefix_groups))
                    prompt = np.concatenate([self.prefixes[group],
                                             tail])
            else:
                group, prompt = -1, tail
            out.append(TrafficRequest(float(arrivals[i]), prompt,
                                      group, tenant))
        return out


async def drive(instance, requests: List[TrafficRequest], *,
                time_scale: float = 1.0,
                latency_slo_ms: Optional[float] = None
                ) -> Dict[str, Any]:
    """Fire `requests` at a deployment instance (``async __call__``
    taking one prompt array) on their arrival schedule.

    time_scale scales modeled arrival offsets to wall time (0.01 turns
    a 50 rps modeled workload into a burst for tests); 0 fires
    everything immediately.  Sheds (:class:`OverloadedError`) are
    counted, not raised.  Returns a report dict with latency
    percentiles over completed requests and, when ``latency_slo_ms``
    is set, the fraction that finished inside the SLO."""
    import asyncio

    t0 = time.perf_counter()

    async def one(req: TrafficRequest) -> Dict[str, Any]:
        delay = req.arrival_s * time_scale - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        start = time.perf_counter()
        try:
            # the tenant tag rides into engine telemetry so per-class
            # anatomy (TTFT p99 by tenant) works without a fleet router
            if req.tenant:
                await instance(req.prompt, tenant=req.tenant)
            else:
                await instance(req.prompt)
        except OverloadedError:
            return {"shed": True, "latency_ms": None}
        return {"shed": False,
                "latency_ms": (time.perf_counter() - start) * 1e3}

    results = await asyncio.gather(*[one(r) for r in requests])
    lat = [r["latency_ms"] for r in results if not r["shed"]]
    shed = sum(1 for r in results if r["shed"])
    report: Dict[str, Any] = {
        "offered": len(requests),
        "completed": len(lat),
        "shed": shed,
        "latency_ms": summarize(lat),
        "wall_s": round(time.perf_counter() - t0, 4),
    }
    if latency_slo_ms is not None:
        report["latency_slo_ms"] = latency_slo_ms
        report["slo_attainment"] = round(
            sum(1 for v in lat if v <= latency_slo_ms) / len(lat), 4) \
            if lat else 0.0
    return report


def run_traffic(spec: TrafficSpec, *, family: str = "gpt2",
                preset: str = "nano", kv_layout: str = "paged",
                kv_block_size: int = 16,
                kv_num_blocks: Optional[int] = None,
                max_slots: int = 4,
                max_new_tokens: int = 8, prefill_bucket: int = 16,
                prefill_chunk_tokens: Optional[int] = None,
                kv_host_tier_bytes: Optional[int] = None,
                time_scale: float = 0.0,
                latency_slo_ms: Optional[float] = None,
                admission_policy=None, slo=None, spec_decode=None,
                mesh=None,
                config_overrides: Optional[Dict[str, Any]] = None,
                trace_dump: Optional[str] = None
                ) -> Dict[str, Any]:
    """One synthetic-traffic run against a fresh in-process engine
    (no serve cluster: the deployment class is instantiated directly,
    same trick the serve tests use).  Returns the :func:`drive` report
    plus the engine's ``engine_stats()`` snapshot — prefix-hit rate
    and kv_cache occupancy ride along when ``kv_layout="paged"``.
    `mesh` tensor-parallelises the engine (see build_llm_deployment);
    the report then carries the engine's mesh block for per-chip
    normalisation downstream (bench --traffic, SWEEPJSON).

    `latency_slo_ms` keeps the legacy client-side measure: the single
    ``slo_attainment`` fraction of completed requests inside one e2e
    latency bound.  `slo` (a serve.slo.SLOConfig) is the engine-side
    richer form — per-objective (TTFT / e2e / queue-wait) attainment
    lands in ``report["slo"]`` and burn rates in
    ``report["engine"]["slo"]``.  `spec_decode` (a SpecConfig) runs
    the traffic through the speculative engine; accept-rate/rounds
    then ride in ``report["spec_accept_rate"]``/``["spec_rounds"]`` so
    ledger series cover spec+traffic runs.

    `prefill_chunk_tokens` enables chunked streaming prefill (paged
    layout only — see build_llm_deployment); the report then carries
    the engine's ``prefill_chunks`` counter block and per-tenant
    ``{tenant}_ttft_ms_p99`` fields so sweeps can A/B the chunk size
    against interactive-tenant TTFT.

    `kv_host_tier_bytes` enables the tiered host-RAM KV cache (see
    build_llm_deployment); ``report["kv_tier_hit_rate"]`` then rides
    along so sweeps can A/B the tier budget against
    ``reprefill_waste_frac`` on churn traffic."""
    import asyncio

    from ray_tpu.serve.llm import build_llm_deployment

    dep = build_llm_deployment(
        family, preset, scheduler="continuous", max_slots=max_slots,
        max_new_tokens=max_new_tokens, temperature=0.0,
        prefill_bucket=prefill_bucket, kv_layout=kv_layout,
        kv_block_size=kv_block_size, kv_num_blocks=kv_num_blocks,
        prefill_chunk_tokens=prefill_chunk_tokens,
        kv_host_tier_bytes=kv_host_tier_bytes,
        admission_policy=admission_policy, slo=slo,
        spec_decode=spec_decode, mesh=mesh,
        config_overrides=config_overrides)
    requests = TrafficGenerator(spec).requests()

    async def main():
        inst = dep.func_or_class()
        try:
            report = await drive(inst, requests,
                                 time_scale=time_scale,
                                 latency_slo_ms=latency_slo_ms)
            report["engine"] = inst.engine_stats()
            if trace_dump:  # tracebus snapshot, pre-shutdown
                from ray_tpu.tools import tracebus

                tracebus.write_dump(tracebus.collect(inst),
                                    trace_dump)
        finally:
            inst.shutdown_engine()
        return report

    report = asyncio.run(main())
    report["spec"] = dataclasses.asdict(spec)
    report["kv_layout"] = kv_layout
    eng = report["engine"]
    kv = eng.get("kv_cache") or {}
    report["prefix_hit_rate"] = kv.get("prefix_hit_rate", 0.0)
    # kvscope headlines: cache pressure (occupancy) and cache-thrash
    # waste (fraction of prefilled tokens that re-filled previously
    # resident prefixes), flattened for SWEEPJSON consumers
    scope_blk = eng.get("kv_scope") or {}
    report["kv_occupancy_p95"] = \
        (scope_blk.get("occupancy") or {}).get("occupancy_p95", 0.0)
    report["reprefill_waste_frac"] = \
        (scope_blk.get("forensics") or {}).get(
            "reprefill_waste_frac", 0.0)
    # host-tier headline: fraction of second-chance probes that
    # restored a block via H2D instead of re-prefilling (0.0 when the
    # tier is off — the field is always present for sweep identity)
    report["kv_tier_hit_rate"] = \
        (eng.get("kv_tier") or {}).get("hit_rate", 0.0)
    # engine-side SLO: per-objective attainment (TTFT + e2e + queue
    # wait as configured), flattened for SWEEPJSON consumers
    slo_block = eng.get("slo")
    if isinstance(slo_block, dict):
        report["slo"] = {
            name: {"target_ms": obj["target_ms"],
                   "attainment": obj["attainment"],
                   "burn_rate": obj["burn_rate"]}
            for name, obj in slo_block["objectives"].items()}
    if spec_decode is not None:
        sp = eng.get("spec") or {}
        report["spec_accept_rate"] = sp.get("accept_rate")
        report["spec_rounds"] = sp.get("rounds")
    report["prefill_chunk_tokens"] = prefill_chunk_tokens
    if eng.get("prefill_chunks"):
        report["prefill_chunks"] = eng["prefill_chunks"]
    _flatten_anatomy(report, eng.get("latency_anatomy"))
    # per-tenant TTFT percentiles, flattened for SWEEPJSON consumers
    # ({tenant}_ttft_ms_p99 — the chunked-prefill headline metric)
    by_tenant = (eng.get("latency_anatomy") or {}).get(
        "by_tenant") or {}
    for tname, blk in by_tenant.items():
        ttft = blk.get("ttft_ms") or {}
        report[f"{tname}_ttft_ms_p99"] = ttft.get("p99")
    return report


#: TTFT-side legs of the tracebus critical path (everything before the
#: first token; the decode-side legs are inter_token + spec_rollback)
_TTFT_COMPONENTS = ("router_wait_ms", "queue_wait_ms", "requeue_ms",
                    "kv_fetch_ms", "prefill_ms", "prefill_wait_ms")


def _flatten_anatomy(report: Dict[str, Any],
                     anatomy: Optional[Dict[str, Any]]) -> None:
    """Lift the headline tracebus numbers out of a latency_anatomy
    block into top-level report fields (itl_ms_p50/p99 +
    ttft_critical_path) for SWEEPJSON consumers."""
    anatomy = anatomy or {}
    report["latency_anatomy"] = anatomy
    itl = anatomy.get("itl_ms") or {}
    report["itl_ms_p50"] = itl.get("p50")
    report["itl_ms_p99"] = itl.get("p99")
    cp = anatomy.get("critical_path") or {}
    ttft: Dict[str, Any] = {k: (cp.get(k) or {}).get("p99")
                            for k in _TTFT_COMPONENTS}
    vals = [v for v in ttft.values() if v is not None]
    ttft["total_p99_ms"] = round(sum(vals), 3) if vals else None
    report["ttft_critical_path"] = ttft


async def drive_fleet(fleet, requests: List[TrafficRequest], *,
                      time_scale: float = 1.0) -> Dict[str, Any]:
    """:func:`drive` for an :class:`~ray_tpu.serve.router.LLMFleet`:
    requests carry their tenant tag into the router (WFQ class +
    per-tenant SLO slicing).  Client-side latency percentiles are
    reported overall and per tenant; engine-side per-tenant attainment
    comes from ``fleet.tenant_report()`` afterwards."""
    import asyncio

    t0 = time.perf_counter()

    async def one(req: TrafficRequest) -> Dict[str, Any]:
        delay = req.arrival_s * time_scale - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        start = time.perf_counter()
        try:
            await fleet(req.prompt, tenant=req.tenant or None)
        except OverloadedError:
            return {"shed": True, "tenant": req.tenant,
                    "latency_ms": None}
        return {"shed": False, "tenant": req.tenant,
                "latency_ms": (time.perf_counter() - start) * 1e3}

    results = await asyncio.gather(*[one(r) for r in requests])
    lat = [r["latency_ms"] for r in results if not r["shed"]]
    by_tenant: Dict[str, List[float]] = {}
    for r in results:
        if not r["shed"]:
            by_tenant.setdefault(r["tenant"] or "default",
                                 []).append(r["latency_ms"])
    return {
        "offered": len(requests),
        "completed": len(lat),
        "shed": sum(1 for r in results if r["shed"]),
        "latency_ms": summarize(lat),
        "latency_ms_by_tenant": {t: summarize(v)
                                 for t, v in by_tenant.items()},
        "wall_s": round(time.perf_counter() - t0, 4),
    }


def run_traffic_fleet(spec: TrafficSpec, *, num_replicas: int = 2,
                      num_prefill_replicas: Optional[int] = None,
                      num_decode_replicas: Optional[int] = None,
                      prefill_engine_kw: Optional[Dict[str, Any]] = None,
                      decode_engine_kw: Optional[Dict[str, Any]] = None,
                      handoff_staged: bool = False,
                      family: str = "gpt2", preset: str = "nano",
                      kv_block_size: int = 16,
                      kv_num_blocks: Optional[int] = None,
                      kv_host_tier_bytes: Optional[int] = None,
                      max_slots: int = 4,
                      max_new_tokens: int = 8,
                      prefill_bucket: int = 16,
                      time_scale: float = 0.0,
                      routing: str = "prefix", wfq: bool = True,
                      autoscale=None, slo=None, admission_policy=None,
                      mesh=None,
                      config_overrides: Optional[Dict[str, Any]] = None,
                      trace_dump: Optional[str] = None,
                      health=None, chaos=None,
                      max_inflight_per_replica: Optional[int] = None
                      ) -> Dict[str, Any]:
    """One multi-tenant traffic run against a fresh in-process fleet
    (``build_llm_fleet``): N paged continuous engines behind the
    prefix-affinity router with WFQ tenant classes.  The report merges
    the client-side :func:`drive_fleet` numbers with the fleet's own
    stats — ``router_prefix_hit_rate`` (pooled over replicas) and
    ``tenants`` (per-tenant SLO attainment) are the headline fields
    bench/sweep publish.

    Setting both `num_prefill_replicas` and `num_decode_replicas`
    runs the DISAGGREGATED fleet instead (role-split replica sets with
    block-granular KV handoff — see build_llm_fleet); the report then
    carries ``handoff_ms_p99`` (the pooled handoff leg of the critical
    path), the fleet ``handoff`` counter block, and ``{role}_``-
    prefixed pool-utilization lines so a sweep can A/B disagg vs
    homogeneous at equal chip count.  `prefill_engine_kw` /
    `decode_engine_kw` overlay per-role engine knobs (mesh degree,
    batch shape, slot count); `handoff_staged` forces the D2H→H2D
    host-staging hop.

    `health` (a serve.health.HealthConfig) tunes the fleet's
    healthwatch monitor; `chaos` (a serve.chaos.ChaosConfig) injects
    seeded faults mid-traffic — the report then carries
    ``time_to_detect_ms`` (fault instant → DEAD transition) and
    ``requests_requeued_on_death`` so sweeps can track detection
    latency as a first-class metric (Podracer treats it as one)."""
    import asyncio

    from ray_tpu.serve.router import build_llm_fleet

    fleet = build_llm_fleet(
        family, preset, num_replicas=num_replicas,
        num_prefill_replicas=num_prefill_replicas,
        num_decode_replicas=num_decode_replicas,
        prefill_engine_kw=prefill_engine_kw,
        decode_engine_kw=decode_engine_kw,
        handoff_staged=handoff_staged,
        tenants=[t.to_class() for t in spec.tenants],
        routing=routing, wfq=wfq, autoscale=autoscale,
        max_slots=max_slots, max_new_tokens=max_new_tokens,
        temperature=0.0, prefill_bucket=prefill_bucket,
        kv_block_size=kv_block_size, kv_num_blocks=kv_num_blocks,
        kv_host_tier_bytes=kv_host_tier_bytes, slo=slo,
        admission_policy=admission_policy, mesh=mesh,
        config_overrides=config_overrides, health=health, chaos=chaos,
        max_inflight_per_replica=max_inflight_per_replica)
    requests = TrafficGenerator(spec).requests()

    async def main():
        try:
            report = await drive_fleet(fleet, requests,
                                       time_scale=time_scale)
            report["fleet"] = fleet.fleet_stats()
            if trace_dump:  # tracebus snapshot, pre-shutdown
                from ray_tpu.tools import tracebus

                tracebus.write_dump(tracebus.collect(fleet),
                                    trace_dump)
        finally:
            fleet.shutdown()
        return report

    report = asyncio.run(main())
    report["spec"] = dataclasses.asdict(spec)
    report["num_replicas"] = num_replicas
    report["num_prefill_replicas"] = num_prefill_replicas
    report["num_decode_replicas"] = num_decode_replicas
    report["handoff_staged"] = handoff_staged
    report["routing"] = routing
    report["wfq"] = wfq
    report["router_prefix_hit_rate"] = \
        report["fleet"]["prefix_hit_rate"]
    # fleet-pooled kvscope headlines (see fleet_stats()["kv_scope"])
    fleet_scope = report["fleet"].get("kv_scope") or {}
    report["kv_occupancy_p95"] = \
        fleet_scope.get("occupancy_p95", 0.0)
    report["reprefill_waste_frac"] = \
        fleet_scope.get("reprefill_waste_frac", 0.0)
    # fleet-pooled host-tier headline (see fleet_stats()["kv_tier"])
    report["kv_tier_hit_rate"] = \
        (report["fleet"].get("kv_tier") or {}).get("hit_rate", 0.0)
    # role-aware pool utilization: one `{role}_`-prefixed line per
    # replica role so a disagg run's decode-pool pressure is never
    # averaged into the prefill pools' churn (monolithic fleets emit
    # the single `both_` role)
    for role, occ in (fleet_scope.get("occupancy_by_role")
                      or {}).items():
        report[f"{role}_kv_occupancy_mean"] = occ.get("mean", 0.0)
        report[f"{role}_kv_occupancy_p95"] = occ.get("p95", 0.0)
    # disaggregation headlines: the fleet handoff counter block and
    # the pooled handoff leg of the critical path (0.0 on homogeneous
    # fleets so sweep identity stays stable)
    report["handoff"] = report["fleet"].get("handoff")
    cp_blk = (report["fleet"].get("latency_anatomy") or {}).get(
        "critical_path") or {}
    report["handoff_ms_p99"] = \
        (cp_blk.get("handoff_ms") or {}).get("p99") or 0.0
    # healthwatch headlines: fault-injection detection latency and
    # queue rescues (None/0 on chaos-free runs so sweep identity
    # stays stable — the fields are always present)
    health_blk = report["fleet"].get("health") or {}
    report["time_to_detect_ms"] = health_blk.get("time_to_detect_ms")
    report["requests_requeued_on_death"] = int(
        health_blk.get("requeued_on_death", 0))
    report["tenants"] = report["fleet"]["tenants"]
    #: flattened for SWEEPJSON consumers: {tenant}_{obj}_slo_attainment
    flat: Dict[str, Any] = {}
    for tname, blk in report["tenants"].items():
        for obj, o in blk["objectives"].items():
            flat[f"{tname}_{obj}_slo_attainment"] = o["attainment"]
    report["tenant_slo_attainment"] = flat
    _flatten_anatomy(report, report["fleet"].get("latency_anatomy"))
    by_tenant = (report["fleet"].get("latency_anatomy") or {}).get(
        "by_tenant") or {}
    for tname, blk in by_tenant.items():
        ttft = blk.get("ttft_ms") or {}
        report[f"{tname}_ttft_ms_p99"] = ttft.get("p99")
    return report
