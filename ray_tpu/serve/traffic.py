"""Synthetic serve traffic: seeded Poisson arrivals over shared-prefix
prompt mixtures.

Serving benchmarks lie unless the offered load looks like production:
requests arrive in bursts (Poisson, not back-to-back), prompts cluster
around a few hot system-prompt prefixes (what the paged KV cache's
prefix reuse exists for), and lengths are ragged.  This module is the
single source of that workload for tests, ``bench.py --traffic`` and
``sweep_tpu.py`` traffic variants — everything is derived from one
integer seed, so a run is reproducible down to the token.

Pieces:

* :class:`TrafficSpec` — the workload knobs (rate, prefix groups,
  length distributions), a frozen dataclass so specs can be shared;
* :class:`TrafficGenerator` — expands a spec into concrete
  ``TrafficRequest`` records (arrival offset + int32 prompt array);
* :func:`drive` — fires the requests at an engine instance on their
  (optionally time-scaled) arrival schedule and measures per-request
  latency, shed count, and SLO attainment;
* :func:`run_traffic` — sync wrapper: builds the LLM deployment,
  drives it, merges ``engine_stats()`` into the report.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu._private.telemetry import summarize
from ray_tpu.serve.batching import OverloadedError

__all__ = ["TrafficSpec", "TrafficRequest", "TrafficGenerator",
           "drive", "run_traffic"]


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """Knobs for one synthetic workload.  All randomness flows from
    ``seed`` through one ``np.random.RandomState``, so equal specs
    generate equal traffic on any host."""

    num_requests: int = 32
    seed: int = 0
    #: Poisson arrival rate (requests/second of *modeled* time);
    #: ``drive(time_scale=...)`` compresses it for fast tests.
    rate_rps: float = 50.0
    #: distinct shared prefixes ("system prompts") in the mixture
    num_prefix_groups: int = 4
    #: tokens per shared prefix (block-aligned values exercise full
    #: reuse; off-aligned values exercise the COW boundary)
    prefix_len: int = 32
    #: probability a request extends one of the shared prefixes
    #: (otherwise its whole prompt is unique)
    p_shared: float = 0.75
    #: request tail (user turn) length ~ 1 + Poisson(mean - 1)
    tail_len_mean: float = 8.0
    tail_len_max: int = 24
    vocab: int = 256

    def __post_init__(self):
        if self.num_requests < 1:
            raise ValueError("num_requests must be >= 1")
        if self.rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        if not 0.0 <= self.p_shared <= 1.0:
            raise ValueError("p_shared must be in [0, 1]")


@dataclasses.dataclass
class TrafficRequest:
    arrival_s: float          # offset from the start of the run
    prompt: np.ndarray        # int32 (len,)
    group: int                # shared-prefix group id, -1 = unique


class TrafficGenerator:
    """Expands a :class:`TrafficSpec` into a concrete request list."""

    def __init__(self, spec: TrafficSpec):
        self.spec = spec
        self._rng = np.random.RandomState(spec.seed)
        # tokens drawn from [2, vocab): 0/1 stay reserved so traffic
        # never collides with pad/bos conventions in the model zoo
        self.prefixes = [
            self._rng.randint(2, spec.vocab, size=spec.prefix_len)
            .astype(np.int32)
            for _ in range(spec.num_prefix_groups)]

    def requests(self) -> List[TrafficRequest]:
        spec, rng = self.spec, self._rng
        inter = rng.exponential(1.0 / spec.rate_rps,
                                size=spec.num_requests)
        arrivals = np.cumsum(inter)
        out: List[TrafficRequest] = []
        for i in range(spec.num_requests):
            tail_len = 1 + min(int(rng.poisson(
                max(spec.tail_len_mean - 1.0, 0.0))),
                spec.tail_len_max - 1)
            tail = rng.randint(2, spec.vocab,
                               size=tail_len).astype(np.int32)
            if spec.num_prefix_groups > 0 \
                    and rng.rand() < spec.p_shared:
                group = int(rng.randint(spec.num_prefix_groups))
                prompt = np.concatenate([self.prefixes[group], tail])
            else:
                group, prompt = -1, tail
            out.append(TrafficRequest(float(arrivals[i]), prompt,
                                      group))
        return out


async def drive(instance, requests: List[TrafficRequest], *,
                time_scale: float = 1.0,
                latency_slo_ms: Optional[float] = None
                ) -> Dict[str, Any]:
    """Fire `requests` at a deployment instance (``async __call__``
    taking one prompt array) on their arrival schedule.

    time_scale scales modeled arrival offsets to wall time (0.01 turns
    a 50 rps modeled workload into a burst for tests); 0 fires
    everything immediately.  Sheds (:class:`OverloadedError`) are
    counted, not raised.  Returns a report dict with latency
    percentiles over completed requests and, when ``latency_slo_ms``
    is set, the fraction that finished inside the SLO."""
    import asyncio

    t0 = time.perf_counter()

    async def one(req: TrafficRequest) -> Dict[str, Any]:
        delay = req.arrival_s * time_scale - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        start = time.perf_counter()
        try:
            await instance(req.prompt)
        except OverloadedError:
            return {"shed": True, "latency_ms": None}
        return {"shed": False,
                "latency_ms": (time.perf_counter() - start) * 1e3}

    results = await asyncio.gather(*[one(r) for r in requests])
    lat = [r["latency_ms"] for r in results if not r["shed"]]
    shed = sum(1 for r in results if r["shed"])
    report: Dict[str, Any] = {
        "offered": len(requests),
        "completed": len(lat),
        "shed": shed,
        "latency_ms": summarize(lat),
        "wall_s": round(time.perf_counter() - t0, 4),
    }
    if latency_slo_ms is not None:
        report["latency_slo_ms"] = latency_slo_ms
        report["slo_attainment"] = round(
            sum(1 for v in lat if v <= latency_slo_ms) / len(lat), 4) \
            if lat else 0.0
    return report


def run_traffic(spec: TrafficSpec, *, family: str = "gpt2",
                preset: str = "nano", kv_layout: str = "paged",
                kv_block_size: int = 16, max_slots: int = 4,
                max_new_tokens: int = 8, prefill_bucket: int = 16,
                time_scale: float = 0.0,
                latency_slo_ms: Optional[float] = None,
                admission_policy=None, slo=None, spec_decode=None,
                mesh=None,
                config_overrides: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
    """One synthetic-traffic run against a fresh in-process engine
    (no serve cluster: the deployment class is instantiated directly,
    same trick the serve tests use).  Returns the :func:`drive` report
    plus the engine's ``engine_stats()`` snapshot — prefix-hit rate
    and kv_cache occupancy ride along when ``kv_layout="paged"``.
    `mesh` tensor-parallelises the engine (see build_llm_deployment);
    the report then carries the engine's mesh block for per-chip
    normalisation downstream (bench --traffic, SWEEPJSON).

    `latency_slo_ms` keeps the legacy client-side measure: the single
    ``slo_attainment`` fraction of completed requests inside one e2e
    latency bound.  `slo` (a serve.slo.SLOConfig) is the engine-side
    richer form — per-objective (TTFT / e2e / queue-wait) attainment
    lands in ``report["slo"]`` and burn rates in
    ``report["engine"]["slo"]``.  `spec_decode` (a SpecConfig) runs
    the traffic through the speculative engine; accept-rate/rounds
    then ride in ``report["spec_accept_rate"]``/``["spec_rounds"]`` so
    ledger series cover spec+traffic runs."""
    import asyncio

    from ray_tpu.serve.llm import build_llm_deployment

    dep = build_llm_deployment(
        family, preset, scheduler="continuous", max_slots=max_slots,
        max_new_tokens=max_new_tokens, temperature=0.0,
        prefill_bucket=prefill_bucket, kv_layout=kv_layout,
        kv_block_size=kv_block_size,
        admission_policy=admission_policy, slo=slo,
        spec_decode=spec_decode, mesh=mesh,
        config_overrides=config_overrides)
    requests = TrafficGenerator(spec).requests()

    async def main():
        inst = dep.func_or_class()
        try:
            report = await drive(inst, requests,
                                 time_scale=time_scale,
                                 latency_slo_ms=latency_slo_ms)
            report["engine"] = inst.engine_stats()
        finally:
            inst.shutdown_engine()
        return report

    report = asyncio.run(main())
    report["spec"] = dataclasses.asdict(spec)
    report["kv_layout"] = kv_layout
    eng = report["engine"]
    kv = eng.get("kv_cache") or {}
    report["prefix_hit_rate"] = kv.get("prefix_hit_rate", 0.0)
    # engine-side SLO: per-objective attainment (TTFT + e2e + queue
    # wait as configured), flattened for SWEEPJSON consumers
    slo_block = eng.get("slo")
    if isinstance(slo_block, dict):
        report["slo"] = {
            name: {"target_ms": obj["target_ms"],
                   "attainment": obj["attainment"],
                   "burn_rate": obj["burn_rate"]}
            for name, obj in slo_block["objectives"].items()}
    if spec_decode is not None:
        sp = eng.get("spec") or {}
        report["spec_accept_rate"] = sp.get("accept_rate")
        report["spec_rounds"] = sp.get("rounds")
    return report
