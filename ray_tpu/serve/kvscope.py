"""kvscope — KV-cache & HBM memory observatory (host-side core).

The observability stack watches *time* end to end (tracebus journal,
flightrec, SLO burn rates); this module watches *memory*.  Three
concerns, all pure host bookkeeping hanging off `BlockPager`
(serve/kv_pager.py) callbacks:

  * **occupancy timelines** — a bounded ring of per-wave pool
    snapshots (free / cached-LRU / in-use / null counts plus a
    fragmentation figure: the largest-contiguous-free-run deficit),
    sampled once per engine wave so a postmortem can replay pool
    pressure around an anomaly without journaling every allocation;
  * **eviction forensics + re-prefill waste** — prefix keys are
    content-addressed token tuples, so an evicted key that later
    re-registers is the SAME prefix being re-filled from scratch.
    Each such re-registration books ``block_size`` tokens of
    `reprefill_waste_tokens` — exactly the tokens the host-RAM KV
    tier (serve/kv_tier.py) saves — broken down per key and per
    tenant.  A key the tier restores instead (``note_tier_hit``)
    books ``tier_hits``/``tokens_restored`` waste-AVOIDED, never
    waste: the forensics split residual churn cost from churn the
    tier absorbed;
  * **unified HBM ledger** — one per-chip table merging the pager's
    pool bytes, jax `device_memory_stats()`, and graftcheck's
    per-program peak budgets into a single ``headroom_bytes`` that an
    `AdmissionPolicy(min_headroom_bytes=)` gate can shed against.

Everything is perf_counter-clocked (graftcheck's
`wallclock-in-telemetry` rule covers this file) and kill-switched by
``RAYTPU_KVSCOPE=0``, mirroring the flight recorder's contract: a
disabled scope costs one attribute check per hook.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["KVScope", "empty_kv_scope", "hbm_ledger",
           "serve_program_budget_bytes"]

#: occupancy ring length — one entry per engine wave, so at the
#: default this is the last ~512 waves of pool history
_RING_CAPACITY = 512
#: evicted-key ledger bound: beyond this the coldest evicted keys are
#: forgotten (counted in ``keys_forgotten``) rather than tracked
_KEY_CAP = 1024
#: per-key waste table bound (top offenders only need so many rows)
_WASTE_KEY_CAP = 256


def _pct(samples: Sequence[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[idx]


class KVScope:
    """Occupancy ring + eviction/re-prefill ledger for one pager.

    The pager owns exactly one of these and calls the ``note_*`` /
    ``sample`` hooks from its own mutation paths; nothing here touches
    the free list or refcounts.  All hooks are O(1) (the fragmentation
    scan is O(free) but runs only on `sample`, once per wave).
    """

    def __init__(self, num_blocks: int, block_size: int, *,
                 ring_capacity: int = _RING_CAPACITY,
                 key_cap: int = _KEY_CAP,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("RAYTPU_KVSCOPE", "1") != "0"
        self.enabled = bool(enabled)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.ring_capacity = int(ring_capacity)
        self._key_cap = int(key_cap)
        #: occupancy ring: dicts of t_s/free/cached/in_use/null/frag
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.ring_capacity)
        #: live block -> tenant attribution (referenced blocks only;
        #: cleared when the block parks or frees — a parked block's
        #: attribution lives on its key, below)
        self._block_tenant: Dict[int, str] = {}
        #: resident prefix key -> tenant that registered it (pruned on
        #: evict, bounded by resident keys <= num_blocks)
        self._key_tenant: Dict[Tuple[int, ...], Optional[str]] = {}
        #: evicted-key ledger: key -> tenant at eviction time, LRU
        #: order == eviction order, bounded by key_cap
        self._evicted: "collections.OrderedDict[Tuple[int, ...], "\
            "Optional[str]]" = collections.OrderedDict()
        self.keys_evicted = 0
        self.keys_forgotten = 0
        self.reprefill_events = 0
        self.reprefill_waste_tokens = 0
        #: host-tier second chances (serve/kv_tier.py): keys restored
        #: via H2D copy instead of re-prefill — waste AVOIDED, kept
        #: beside the residual waste so the split is visible
        self.tier_hits = 0
        self.tokens_restored = 0
        self._waste_by_tenant: Dict[str, int] = {}
        self._waste_by_key: Dict[Tuple[int, ...], int] = {}

    # -- occupancy -----------------------------------------------------

    def sample(self, free_ids: Sequence[int], cached: int) -> None:
        """Append one pool snapshot to the ring (engine calls this
        once per wave).  ``in_use`` counts every block not free and
        not parked — including the reserved null block — so the ring
        invariant ``free + cached + in_use == num_blocks`` holds
        exactly at every sample."""
        if not self.enabled:
            return
        free = len(free_ids)
        in_use = self.num_blocks - free - int(cached)
        self._ring.append({
            "t_s": time.perf_counter(),
            "free": free,
            "cached": int(cached),
            "in_use": in_use,
            "null": 1,
            "frag": self._fragmentation(free_ids),
        })

    def _fragmentation(self, free_ids: Sequence[int]) -> float:
        """Largest-contiguous-run deficit over the free list: 0.0 when
        every free block sits in one contiguous id run (a maximal
        sequence could land without interleaving), approaching 1.0 as
        the free space shatters into single blocks."""
        n = len(free_ids)
        if n <= 1:
            return 0.0
        ids = sorted(free_ids)
        longest = run = 1
        for prev, cur in zip(ids, ids[1:]):
            run = run + 1 if cur == prev + 1 else 1
            if run > longest:
                longest = run
        return round(1.0 - longest / n, 4)

    def occupancy_ratio(self, free: int, cached: int) -> float:
        """Fraction of the usable pool (null excluded) not on the
        free list — in-use plus parked-LRU blocks."""
        usable = max(1, self.num_blocks - 1)
        return round(1.0 - free / usable, 4)

    # -- tenant attribution --------------------------------------------

    def note_alloc(self, block_ids: Sequence[int],
                   tenant: Optional[str]) -> None:
        """Attribute freshly-allocated or revived blocks to the tenant
        in the pager's request context (None drops attribution)."""
        if not self.enabled:
            return
        if tenant:
            for blk in block_ids:
                self._block_tenant[blk] = tenant
        else:
            for blk in block_ids:
                self._block_tenant.pop(blk, None)

    def note_block_released(self, block_id: int) -> None:
        """The block reached refcount 0 (parked or freed) — live
        attribution ends; a parked block's tenant survives on its
        registered key."""
        self._block_tenant.pop(block_id, None)

    # -- eviction forensics + re-prefill waste -------------------------

    def note_register(self, key: Tuple[int, ...],
                      tenant: Optional[str]) -> int:
        """One prefix key became resident.  If the key was previously
        evicted this registration IS a re-prefill of content the pool
        once held: book ``block_size`` waste tokens against the key
        and the registering tenant.  Returns the tokens booked (0 for
        a first-time key) so the pager can journal the event."""
        if not self.enabled:
            return 0
        self._key_tenant[key] = tenant
        if key not in self._evicted:
            return 0
        del self._evicted[key]
        waste = self.block_size
        self.reprefill_events += 1
        self.reprefill_waste_tokens += waste
        if tenant:
            self._waste_by_tenant[tenant] = \
                self._waste_by_tenant.get(tenant, 0) + waste
        if len(self._waste_by_key) < _WASTE_KEY_CAP \
                or key in self._waste_by_key:
            self._waste_by_key[key] = \
                self._waste_by_key.get(key, 0) + waste
        return waste

    def note_tier_hit(self, key: Tuple[int, ...],
                      tenant: Optional[str]) -> None:
        """One prefix key was restored from the host KV tier
        (H2D copy) instead of being re-prefilled.  Consumes the
        evicted-ledger entry WITHOUT booking waste — the later
        ``note_register`` of the same key (the pager re-indexes the
        restored block) must book zero ``reprefill_waste_tokens`` —
        and records the avoided work as ``tokens_restored``."""
        if not self.enabled:
            return
        self.tier_hits += 1
        self.tokens_restored += self.block_size
        self._key_tenant[key] = tenant
        if key in self._evicted:
            del self._evicted[key]

    def note_handoff_import(self, key: Tuple[int, ...],
                            tenant: Optional[str]) -> None:
        """One prefix key became resident via a disaggregated handoff
        install (serve/router.py two-stage dispatch: block rows copied
        in from a prefill replica's pool).  Consumes the
        evicted-ledger entry WITHOUT booking waste — the content
        arrived by copy, not re-prefill — and without tier counters
        (no host tier was involved)."""
        if not self.enabled:
            return
        self._key_tenant[key] = tenant
        if key in self._evicted:
            del self._evicted[key]

    def note_evict(self, key: Optional[Tuple[int, ...]]
                   ) -> Optional[str]:
        """One registered block was LRU-evicted.  Moves the key into
        the evicted ledger (bounded — the coldest tracked evictions
        are forgotten, not leaked) and returns the owning tenant for
        the pager's journal event."""
        if not self.enabled or key is None:
            return None
        tenant = self._key_tenant.pop(key, None)
        self.keys_evicted += 1
        self._evicted[key] = tenant
        self._evicted.move_to_end(key)
        while len(self._evicted) > self._key_cap:
            self._evicted.popitem(last=False)
            self.keys_forgotten += 1
        return tenant

    # -- introspection -------------------------------------------------

    def blocks_by_tenant(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for tenant in self._block_tenant.values():
            out[tenant] = out.get(tenant, 0) + 1
        return out

    def stats(self, *, free: int, cached: int,
              prefill_tokens: int = 0) -> Dict[str, object]:
        """The ``kv_scope`` occupancy/forensics block (the HBM ledger
        is composed by the deployment, which owns the device view)."""
        ratios = [self.occupancy_ratio(s["free"], s["cached"])
                  for s in self._ring]
        frags = [s["frag"] for s in self._ring]
        waste = self.reprefill_waste_tokens
        top = sorted(self._waste_by_key.items(),
                     key=lambda kv: -kv[1])[:8]
        return {
            "enabled": self.enabled,
            "occupancy": {
                "ring_capacity": self.ring_capacity,
                "samples": len(self._ring),
                "last": dict(self._ring[-1]) if self._ring else None,
                "occupancy_ratio": self.occupancy_ratio(free, cached),
                "occupancy_p95": _pct(ratios, 0.95),
                "fragmentation": frags[-1] if frags else 0.0,
                # raw ring, oldest first: the CLI's timeline/export
                # feed — bounded by ring_capacity, so a snapshot stays
                # a few tens of KB at the default
                "ring": self.timeline(),
            },
            "forensics": {
                "keys_evicted": self.keys_evicted,
                "keys_tracked": len(self._evicted),
                "keys_forgotten": self.keys_forgotten,
                "reprefill_events": self.reprefill_events,
                "reprefill_waste_tokens": waste,
                "reprefill_waste_frac":
                    round(waste / prefill_tokens, 4)
                    if prefill_tokens else 0.0,
                "prefill_tokens": int(prefill_tokens),
                "tier_hits": self.tier_hits,
                "tokens_restored": self.tokens_restored,
                "waste_by_tenant": dict(self._waste_by_tenant),
                "top_keys": [
                    {"key_prefix": list(k[:8]), "key_len": len(k),
                     "tokens": v} for k, v in top],
            },
            "blocks_by_tenant": self.blocks_by_tenant(),
        }

    def timeline(self) -> List[Dict[str, object]]:
        """The raw occupancy ring, oldest first (CLI/export feed)."""
        return [dict(s) for s in self._ring]


def empty_kv_scope() -> Dict[str, object]:
    """The stable zero-shaped ``kv_scope`` block dense engines (no
    pager) report — same keys as a live paged block so dashboards and
    the golden-schema test never branch on layout."""
    return {
        "enabled": False,
        "occupancy": {
            "ring_capacity": 0,
            "samples": 0,
            "last": None,
            "occupancy_ratio": 0.0,
            "occupancy_p95": 0.0,
            "fragmentation": 0.0,
            "ring": [],
        },
        "forensics": {
            "keys_evicted": 0,
            "keys_tracked": 0,
            "keys_forgotten": 0,
            "reprefill_events": 0,
            "reprefill_waste_tokens": 0,
            "reprefill_waste_frac": 0.0,
            "prefill_tokens": 0,
            "tier_hits": 0,
            "tokens_restored": 0,
            "waste_by_tenant": {},
            "top_keys": [],
        },
        "blocks_by_tenant": {},
        "hbm_ledger": {"per_chip": [], "min_headroom_bytes": None},
    }


def hbm_ledger(*, pool_bytes_per_chip: int = 0,
               device_stats: Optional[Sequence[Dict]] = None,
               program_budget_bytes: int = 0) -> Dict[str, object]:
    """Unified per-chip HBM table: merges the KV pool's resident
    bytes, the live allocator view (`device_memory_stats()` rows), and
    graftcheck's audited per-program peak budget into one
    ``headroom_bytes`` per chip.

    ``headroom = bytes_limit - max(bytes_in_use, pool + budget)`` —
    the allocator view when it is the larger (live activations beyond
    the audited programs), the static commitment when the allocator
    under-reports (CPU backends report no live bytes at all).  Chips
    with no ``bytes_limit`` (CPU) get ``headroom_bytes: None`` and are
    excluded from ``min_headroom_bytes``, so the AdmissionPolicy gate
    is inert off-accelerator by construction."""
    rows: List[Dict[str, object]] = []
    for d in device_stats or []:
        limit = d.get("bytes_limit")
        in_use = d.get("bytes_in_use")
        committed = max(in_use or 0,
                        pool_bytes_per_chip + program_budget_bytes)
        rows.append({
            "id": d.get("id"),
            "platform": d.get("platform"),
            "bytes_limit": limit,
            "bytes_in_use": in_use,
            "peak_bytes_in_use": d.get("peak_bytes_in_use"),
            "kv_pool_bytes": int(pool_bytes_per_chip),
            "program_budget_bytes": int(program_budget_bytes),
            "headroom_bytes":
                int(limit) - int(committed)
                if limit is not None else None,
        })
    vals = [r["headroom_bytes"] for r in rows
            if r["headroom_bytes"] is not None]
    return {"per_chip": rows,
            "min_headroom_bytes": min(vals) if vals else None}


def serve_program_budget_bytes() -> int:
    """Worst-case audited peak over graftcheck's serve-path programs
    (prefill / decode / verify specs) — the static 'what the jitted
    programs may transiently need' term of the ledger.  Best effort:
    0 when graftcheck is unimportable (the ledger then leans on the
    allocator view alone)."""
    try:
        from ray_tpu.tools.graftcheck.programs import default_programs

        budgets = [
            (spec.per_chip_hbm_budget_bytes
             or spec.hbm_budget_bytes or 0)
            for spec in default_programs()
            if any(tag in spec.name
                   for tag in ("prefill", "decode", "verify"))]
        return max(budgets, default=0)
    except Exception:  # noqa: BLE001 - observability must not raise
        return 0
