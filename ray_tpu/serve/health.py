"""Healthwatch: the fleet health observatory (replica liveness).

``EngineTelemetry`` measures latency and ``SLOTracker`` judges it;
this module answers the operational question neither can: **which
replica is sick, and since when**.  One :class:`HealthMonitor` per
fleet (serve/router.py attaches it) runs a per-replica liveness state
machine over engine-loop heartbeats:

    HEALTHY --heartbeat older than suspect_ms--> SUSPECT
    SUSPECT --heartbeat older than dead_ms-----> DEAD
    any     --heartbeat resumes----------------> HEALTHY (recovered)

* **Heartbeats** — every wave of the continuous engine loop
  (serve/llm.py ``_engine``) stamps a ``perf_counter`` heartbeat; an
  idle-parked loop declares itself idle instead (an idle replica has
  no outstanding work, so a stale heartbeat there is not a failure).
* **Stall detection** — a request that was admitted but has been
  token-silent past ``stall_ms`` marks its replica SUSPECT and
  journals ``request_stall`` with the flightrec-known resident state
  (slot, tokens emitted, silence), so a wedged single request is
  visible even while the loop itself still heartbeats.
* **Routing consequences** — the router deprioritizes SUSPECT
  replicas, skips DEAD ones, and push_front-requeues a dead replica's
  queued (not-yet-admitted) requests to healthy replicas
  (``record_requeue(reason="replica_dead")``).
* **Detection latency** — chaos injection (serve/chaos.py) stamps the
  fault instant via :meth:`HealthMonitor.note_fault`; the DEAD
  transition then carries ``time_to_detect_ms``, the first-class
  fault-tolerance metric bench/sweep/perfledger track.

Every transition journals a ``health_transition`` event to the fleet
flight recorder (and the replica's own), counts in
``engine_stats()["health"]`` / ``fleet_stats()["health"]`` (per-role
for disaggregated fleets), and publishes the Prometheus
``serve_replica_health_state`` gauge / ``serve_health_transitions_total``
counter.  ``RAYTPU_HEALTHWATCH=0`` kills the whole observatory (the
flightrec/kvscope convention); disabled monitors hand out the same
zero-shaped blocks so consumers never branch.

Clock discipline matches telemetry: monotonic ``perf_counter`` only,
``now`` injectable everywhere for deterministic tests (enforced by
graftcheck's ``wallclock-in-telemetry`` rule, which covers this file).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["HEALTHY", "SUSPECT", "DEAD", "HealthConfig",
           "HealthMonitor", "empty_health", "empty_fleet_health",
           "healthwatch_enabled"]

HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"

#: gauge encoding for serve_replica_health_state (0 reads "fine" on a
#: dashboard; alerts trigger on >= 1)
_STATE_CODE = {HEALTHY: 0, SUSPECT: 1, DEAD: 2}


def healthwatch_enabled() -> bool:
    """Kill switch, same convention as RAYTPU_KVSCOPE /
    RAYTPU_TRACEBUS: set RAYTPU_HEALTHWATCH=0 to disable."""
    return os.environ.get("RAYTPU_HEALTHWATCH", "1") != "0"


_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None


def _health_metrics() -> Dict[str, Any]:
    """Process-wide serve health metric singletons (same pattern as
    serve/slo.py — one registration per name however many fleets this
    process hosts)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = {
                "state": Gauge(
                    "serve_replica_health_state",
                    "replica liveness state "
                    "(0=healthy, 1=suspect, 2=dead)",
                    tag_keys=("deployment", "replica")),
                "transitions": Counter(
                    "serve_health_transitions_total",
                    "liveness state transitions, by entered state",
                    tag_keys=("deployment", "replica", "state")),
                "stalls": Counter(
                    "serve_request_stalls_total",
                    "admitted requests token-silent past stall_ms",
                    tag_keys=("deployment", "replica")),
            }
        return _metrics


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Liveness thresholds for one fleet's :class:`HealthMonitor`.

    A replica whose last heartbeat is older than ``suspect_ms`` is
    SUSPECT (deprioritized by the router), older than ``dead_ms`` is
    DEAD (skipped; its queued requests requeue to healthy replicas).
    An admitted request token-silent past ``stall_ms`` marks its
    replica SUSPECT even while the loop heartbeats.  ``probe_ms``
    throttles the state-machine sweep (``maybe_probe``); ``history``
    bounds the retained per-replica transition log."""

    suspect_ms: float = 1000.0
    dead_ms: float = 5000.0
    stall_ms: float = 2000.0
    probe_ms: float = 50.0
    history: int = 64

    def __post_init__(self):
        for name, v in (("suspect_ms", self.suspect_ms),
                        ("dead_ms", self.dead_ms),
                        ("stall_ms", self.stall_ms)):
            if v <= 0:
                raise ValueError(f"{name} must be > 0, got {v}")
        if self.dead_ms <= self.suspect_ms:
            raise ValueError(
                f"dead_ms must exceed suspect_ms, got "
                f"suspect={self.suspect_ms} dead={self.dead_ms}")
        if self.probe_ms < 0:
            raise ValueError(
                f"probe_ms must be >= 0, got {self.probe_ms}")
        if self.history < 1:
            raise ValueError(
                f"history must be >= 1, got {self.history}")


def empty_health() -> Dict[str, Any]:
    """The zero-shaped ``engine_stats()["health"]`` block: same keys
    as a live monitor's :meth:`HealthMonitor.replica_block`, all
    zeroed, ``enabled`` False.  Dense engines, fleets with
    RAYTPU_HEALTHWATCH=0, and standalone engines (no fleet, so no
    monitor) all serve this — consumers never branch on presence."""
    return {
        "enabled": False,
        "state": HEALTHY,
        "suspect_ms": 0.0,
        "dead_ms": 0.0,
        "stall_ms": 0.0,
        "heartbeats": 0,
        "heartbeat_age_ms": 0.0,
        "idle": False,
        "transitions": 0,
        "suspect_count": 0,
        "dead_count": 0,
        "recoveries": 0,
        "stalls": 0,
        "time_to_detect_ms": None,
        "transition_log": [],
    }


def empty_fleet_health() -> Dict[str, Any]:
    """The zero-shaped ``fleet_stats()["health"]`` block (monitor
    disabled) — same keys as :meth:`HealthMonitor.fleet_block`."""
    return {
        "enabled": False,
        "config": {"suspect_ms": 0.0, "dead_ms": 0.0,
                   "stall_ms": 0.0},
        "replicas": {},
        "by_state": {HEALTHY: 0, SUSPECT: 0, DEAD: 0},
        "by_role": {},
        "transitions": 0,
        "stalls": 0,
        "faults_injected": 0,
        "requeued_on_death": 0,
        "time_to_detect_ms": None,
    }


class _ReplicaHealth:
    """Internal per-replica liveness record."""

    __slots__ = ("name", "role", "state", "last_beat", "beats",
                 "idle", "transitions", "suspect_count", "dead_count",
                 "recoveries", "stalls", "fault_ts", "fault_kind",
                 "detect_ms", "recorder", "telemetry", "stalled_ids")

    def __init__(self, name: str, role: str, now: float,
                 recorder=None, telemetry=None, history: int = 64):
        self.name = name
        self.role = role
        self.state = HEALTHY
        self.last_beat = now
        self.beats = 0
        self.idle = True
        self.transitions: collections.deque = collections.deque(
            maxlen=history)
        self.suspect_count = 0
        self.dead_count = 0
        self.recoveries = 0
        self.stalls = 0
        self.fault_ts: Optional[float] = None
        self.fault_kind: Optional[str] = None
        self.detect_ms: Optional[float] = None
        self.recorder = recorder
        self.telemetry = telemetry
        self.stalled_ids: set = set()


class HealthMonitor:
    """Per-fleet liveness state machine over engine heartbeats.

    All mutating methods take an optional ``now`` (seconds, from
    ``time.perf_counter()``) so tests can drive deterministic clocks.
    When disabled (RAYTPU_HEALTHWATCH=0 or ``enabled=False``) every
    method is a cheap no-op and the blocks come back zero-shaped."""

    def __init__(self, config: Optional[HealthConfig] = None, *,
                 deployment: str = "llm_fleet", recorder=None,
                 enabled: Optional[bool] = None,
                 now: Optional[float] = None):
        self.config = config or HealthConfig()
        self.deployment = deployment
        self.enabled = (healthwatch_enabled() if enabled is None
                        else bool(enabled))
        #: the FLEET flight recorder — transitions journal here (with
        #: a replica field, the routing-table idiom) and to each
        #: replica's own recorder
        self._recorder = recorder
        #: reentrant: _transition locks itself, and the write paths
        #: (heartbeat, note_fault, the probe sweep) hold the lock
        #: across their compound updates — heartbeat stamps and chaos
        #: faults arrive from different execution contexts than the
        #: probe/reconcile sweeps that read them back
        self._lock = threading.RLock()
        self._reps: Dict[str, _ReplicaHealth] = {}
        self._last_probe: Optional[float] = None
        self.faults_injected = 0
        self.requeued_on_death = 0
        self._m = _health_metrics() if self.enabled else None

    def _now(self, now: Optional[float]) -> float:
        return time.perf_counter() if now is None else now

    # -- registration --------------------------------------------------

    def register(self, replica: str, *, role: str = "both",
                 recorder=None, telemetry=None,
                 now: Optional[float] = None) -> None:
        """Start watching one replica.  ``recorder`` is the replica's
        own flight recorder (transition copies land there too);
        ``telemetry`` its EngineTelemetry, consulted for the stall
        sweep.  Replicas register idle — the first heartbeat arms the
        staleness clock."""
        if not self.enabled:
            return
        now = self._now(now)
        with self._lock:
            self._reps[replica] = _ReplicaHealth(
                replica, role, now, recorder=recorder,
                telemetry=telemetry, history=self.config.history)
        self._m["state"].set(0, tags={"deployment": self.deployment,
                                      "replica": replica})

    def unregister(self, replica: str) -> None:
        """Stop watching a replica (graceful drain/retirement — a
        stopped loop is not a failure)."""
        with self._lock:
            self._reps.pop(replica, None)

    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._reps)

    # -- hot-path stamps (engine loop) ---------------------------------

    def heartbeat(self, replica: str,
                  now: Optional[float] = None) -> None:
        """One engine-wave liveness stamp.  Hot path: a dict lookup
        and one uncontended lock round-trip; the recovery transition
        only runs after a SUSPECT/DEAD episode.  The lock matters:
        ``beats += 1`` is a read-modify-write racing the probe sweep's
        reads from another thread."""
        if not self.enabled:
            return
        rep = self._reps.get(replica)
        if rep is None:
            return
        with self._lock:
            rep.last_beat = self._now(now)
            rep.beats += 1
            rep.idle = False
            if rep.state != HEALTHY:
                self._transition(rep, HEALTHY, rep.last_beat,
                                 reason="heartbeat_resumed")

    def note_idle(self, replica: str,
                  now: Optional[float] = None) -> None:
        """The engine loop is parking with no outstanding work; a
        stale heartbeat while idle is not a failure, so the probe
        skips idle replicas until the next heartbeat."""
        if not self.enabled:
            return
        rep = self._reps.get(replica)
        if rep is None:
            return
        rep.last_beat = self._now(now)
        rep.idle = True

    # -- fault bookkeeping (chaos + router) ----------------------------

    def note_fault(self, replica: str, kind: str = "freeze",
                   now: Optional[float] = None) -> None:
        """Chaos injection stamps the fault instant here so the DEAD
        transition can carry ``time_to_detect_ms`` (fault → detection,
        the metric ROADMAP item 4 treats as first-class)."""
        if not self.enabled:
            return
        rep = self._reps.get(replica)
        if rep is None:
            return
        now = self._now(now)
        with self._lock:
            # one block: the DEAD transition reads fault_ts/detect_ms
            # as a pair to compute time_to_detect_ms — a probe landing
            # between these stores would see a half-initialized fault
            rep.fault_ts = now
            rep.fault_kind = kind
            rep.detect_ms = None
            self.faults_injected += 1
        if self._recorder is not None:
            self._recorder.record("fault_injected", ts=now,
                                  replica=replica, fault=kind)

    def note_requeued(self, n: int = 1) -> None:
        """The router moved `n` of a dead replica's queued requests to
        healthy replicas."""
        with self._lock:
            self.requeued_on_death += int(n)

    # -- the state machine ---------------------------------------------

    def state(self, replica: str) -> str:
        rep = self._reps.get(replica)
        return rep.state if rep is not None else HEALTHY

    def maybe_probe(self, now: Optional[float] = None
                    ) -> List[Dict[str, Any]]:
        """Throttled :meth:`probe` — the form the engine loop and the
        router pump call (one subtraction when inside the window)."""
        if not self.enabled:
            return []
        now = self._now(now)
        with self._lock:
            # check-then-claim atomically: the engine loop and the
            # router pump both throttle through this window, and an
            # unlocked check would let both run the sweep
            if self._last_probe is not None and \
                    now - self._last_probe < self.config.probe_ms / 1e3:
                return []
            self._last_probe = now
        return self.probe(now=now)

    def probe(self, now: Optional[float] = None
              ) -> List[Dict[str, Any]]:
        """One state-machine sweep: age every replica's heartbeat
        through HEALTHY→SUSPECT→DEAD and run the stall sweep over
        admitted-but-token-silent requests.  Returns the transitions
        this sweep produced."""
        if not self.enabled:
            return []
        now = self._now(now)
        self._last_probe = now
        cfg = self.config
        out: List[Dict[str, Any]] = []
        with self._lock:
            reps = list(self._reps.values())
        for rep in reps:
            for stall in self._stall_sweep(rep, now):
                out.append(stall)
            if rep.idle:
                continue
            age_ms = (now - rep.last_beat) * 1e3
            if age_ms >= cfg.dead_ms and rep.state != DEAD:
                out.append(self._transition(
                    rep, DEAD, now, reason="heartbeat_lost",
                    age_ms=age_ms))
            elif age_ms >= cfg.suspect_ms and rep.state == HEALTHY:
                out.append(self._transition(
                    rep, SUSPECT, now, reason="heartbeat_stale",
                    age_ms=age_ms))
        return out

    def _stall_sweep(self, rep: _ReplicaHealth, now: float
                     ) -> List[Dict[str, Any]]:
        """Outstanding-request stall detection: admitted requests
        token-silent past stall_ms journal ``request_stall`` with the
        flightrec-known resident state and suspect the replica (once
        per request)."""
        out: List[Dict[str, Any]] = []
        tele = rep.telemetry
        if tele is None or rep.state == DEAD:
            return out
        fn = getattr(tele, "stalled_requests", None)
        if fn is None:
            return out
        for stall in fn(self.config.stall_ms, now=now):
            if stall["id"] in rep.stalled_ids:
                continue
            with self._lock:
                rep.stalled_ids.add(stall["id"])
                rep.stalls += 1
            fields = dict(stall, replica=rep.name)
            rid = fields.pop("id")
            if fields.get("trace") is None:
                fields.pop("trace", None)
            if rep.recorder is not None:
                rep.recorder.record("request_stall", ts=now, req=rid,
                                    **fields)
            if self._recorder is not None \
                    and self._recorder is not rep.recorder:
                self._recorder.record("request_stall", ts=now,
                                      req=rid, **fields)
            self._m["stalls"].inc(tags={
                "deployment": self.deployment, "replica": rep.name})
            if rep.state == HEALTHY:
                out.append(self._transition(
                    rep, SUSPECT, now, reason="request_stall",
                    age_ms=stall["silent_ms"]))
        return out

    def _transition(self, rep: _ReplicaHealth, to_state: str,
                    now: float, reason: str,
                    age_ms: Optional[float] = None
                    ) -> Dict[str, Any]:
        # reentrant lock: heartbeat/note_fault call in holding it, the
        # probe sweep calls in bare — either way the state flip, the
        # episode counters, and the transition-log append land as one
        # unit against concurrent stats readers
        with self._lock:
            return self._transition_locked(rep, to_state, now, reason,
                                           age_ms)

    def _transition_locked(self, rep: _ReplicaHealth, to_state: str,
                           now: float, reason: str,
                           age_ms: Optional[float] = None
                           ) -> Dict[str, Any]:
        from_state, rep.state = rep.state, to_state
        if to_state == SUSPECT:
            rep.suspect_count += 1
        elif to_state == DEAD:
            rep.dead_count += 1
            if rep.fault_ts is not None and rep.detect_ms is None:
                rep.detect_ms = round((now - rep.fault_ts) * 1e3, 3)
        else:
            rep.recoveries += 1
            rep.stalled_ids.clear()
        tr = {
            "replica": rep.name,
            "from": from_state,
            "to": to_state,
            "reason": reason,
            "ts": now,
            "heartbeat_age_ms": (round(float(age_ms), 3)
                                 if age_ms is not None else 0.0),
        }
        if to_state == DEAD and rep.detect_ms is not None:
            tr["time_to_detect_ms"] = rep.detect_ms
        rep.transitions.append(tr)
        fields = {k: v for k, v in tr.items() if k != "ts"}
        if self._recorder is not None:
            self._recorder.record("health_transition", ts=now,
                                  **fields)
        if rep.recorder is not None \
                and rep.recorder is not self._recorder:
            rep.recorder.record("health_transition", ts=now, **fields)
        tags = {"deployment": self.deployment, "replica": rep.name}
        self._m["state"].set(_STATE_CODE[to_state], tags=tags)
        self._m["transitions"].inc(tags=dict(tags, state=to_state))
        return tr

    # -- derived metrics -----------------------------------------------

    @property
    def time_to_detect_ms(self) -> Optional[float]:
        """Worst (max) fault→DEAD detection latency observed across
        replicas; None until a noted fault has been detected."""
        with self._lock:
            vals = [r.detect_ms for r in self._reps.values()
                    if r.detect_ms is not None]
        return max(vals) if vals else None

    # -- stats blocks --------------------------------------------------

    def replica_block(self, replica: str,
                      now: Optional[float] = None) -> Dict[str, Any]:
        """The per-engine ``engine_stats()["health"]`` block — same
        keys as :func:`empty_health` always."""
        rep = self._reps.get(replica)
        if not self.enabled or rep is None:
            return empty_health()
        now = self._now(now)
        cfg = self.config
        with self._lock:
            # the transition log grows from the probe sweep's thread;
            # iterate it (and read the counters as one consistent
            # snapshot) under the lock
            return {
                "enabled": True,
                "state": rep.state,
                "suspect_ms": cfg.suspect_ms,
                "dead_ms": cfg.dead_ms,
                "stall_ms": cfg.stall_ms,
                "heartbeats": rep.beats,
                "heartbeat_age_ms": round((now - rep.last_beat) * 1e3,
                                          3),
                "idle": rep.idle,
                "transitions": len(rep.transitions),
                "suspect_count": rep.suspect_count,
                "dead_count": rep.dead_count,
                "recoveries": rep.recoveries,
                "stalls": rep.stalls,
                "time_to_detect_ms": rep.detect_ms,
                "transition_log": [dict(t) for t in rep.transitions],
            }

    def fleet_block(self, now: Optional[float] = None
                    ) -> Dict[str, Any]:
        """The ``fleet_stats()["health"]`` block: per-replica state +
        last-heartbeat age + transition history, pooled state counts
        overall and per role (disaggregated fleets keep prefill and
        decode pools apart, the occupancy_by_role idiom)."""
        if not self.enabled:
            return empty_fleet_health()
        now = self._now(now)
        cfg = self.config
        with self._lock:
            reps = list(self._reps.values())
            faults = self.faults_injected
            requeued = self.requeued_on_death
        by_state = {HEALTHY: 0, SUSPECT: 0, DEAD: 0}
        by_role: Dict[str, Dict[str, int]] = {}
        replicas: Dict[str, Any] = {}
        transitions = stalls = 0
        detect: Optional[float] = None
        for rep in reps:
            by_state[rep.state] += 1
            role = by_role.setdefault(
                rep.role, {HEALTHY: 0, SUSPECT: 0, DEAD: 0})
            role[rep.state] += 1
            transitions += len(rep.transitions)
            stalls += rep.stalls
            if rep.detect_ms is not None:
                detect = (rep.detect_ms if detect is None
                          else max(detect, rep.detect_ms))
            replicas[rep.name] = {
                "state": rep.state,
                "role": rep.role,
                "idle": rep.idle,
                "heartbeats": rep.beats,
                "heartbeat_age_ms": round(
                    (now - rep.last_beat) * 1e3, 3),
                "stalls": rep.stalls,
                "time_to_detect_ms": rep.detect_ms,
                "transitions": [dict(t) for t in rep.transitions],
            }
        return {
            "enabled": True,
            "config": {"suspect_ms": cfg.suspect_ms,
                       "dead_ms": cfg.dead_ms,
                       "stall_ms": cfg.stall_ms},
            "replicas": replicas,
            "by_state": by_state,
            "by_role": by_role,
            "transitions": transitions,
            "stalls": stalls,
            "faults_injected": faults,
            "requeued_on_death": requeued,
            "time_to_detect_ms": detect,
        }
