"""DeploymentHandle: client-side router to a deployment's replicas.

Reference analog: serve/handle.py:77 RayServeHandle +
_private/router.py:261 Router (:298 assign_request).  Routing is
least-loaded-of-two (power of two choices by in-flight count tracked
locally).  Replica membership arrives PUSH-style: a daemon listener
thread long-polls the controller's ``listen_for_change`` channel
(reference: serve/_private/long_poll.py:184 LongPollClient) and swaps
the local replica list the moment the controller mutates it — restarts,
autoscaling, and redeploys propagate in one RPC round-trip instead of a
polling interval.  A direct refresh remains the error-path fallback.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

_REFRESH_S = 5.0  # fallback staleness bound if the listener dies


class _SharedListener:
    """ONE long-poll loop per (controller, deployment) per process,
    fanned out to every registered handle via weakrefs.  Bounds the
    controller concurrency slots parked on ``listen_for_change`` at
    #processes × #deployments instead of #handles (reference: one
    LongPollClient per router process, not per handle)."""

    def __init__(self, controller, name: str):
        self._controller = controller
        self._name = name
        self._handles: list = []  # weakrefs
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        #: monotonic time of the last successful listen_for_change
        #: round-trip — handles fall back to polling when this goes
        #: stale (listener wedged/dead)
        self.last_ok = 0.0

    def register(self, handle: "DeploymentHandle") -> None:
        import weakref

        with self._lock:
            # dedupe: a handle re-registers on every request while the
            # listener is unhealthy — without this the list grows
            # unboundedly during a controller outage and each update
            # then fans out once per duplicate
            if not any(ref() is handle for ref in self._handles):
                self._handles.append(weakref.ref(handle))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"serve-longpoll-{self._name}")
                self._thread.start()

    def _live_handles(self) -> list:
        with self._lock:
            out = []
            keep = []
            for ref in self._handles:
                h = ref()
                if h is not None and not h._closed:
                    out.append(h)
                    keep.append(ref)
            self._handles = keep
            return out

    def _loop(self) -> None:
        import ray_tpu

        version = 0
        while True:
            if not self._live_handles():
                with self._lock:
                    if self._handles:
                        # register() raced our empty snapshot: a fresh
                        # handle appeared between the check and this
                        # lock — keep looping for it (lost-wakeup fix)
                        continue
                    self._thread = None  # next register restarts us
                    return
            from ray_tpu._private import worker_context

            cw = worker_context.maybe_core_worker()
            if cw is None or getattr(cw, "_closed", False):
                # the cluster shut down under us: a daemon listener
                # retrying forever against a closed client would touch
                # the unmapped shm store (segfault class) — exit
                with self._lock:
                    self._thread = None
                return
            try:
                out = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._name, version),
                    timeout=60)
                self.last_ok = time.monotonic()
            except Exception:  # noqa: BLE001 - controller briefly away
                time.sleep(1.0)
                continue
            if out.get("version") == -1:
                # deployment deleted: drop out of the registry so a
                # redeploy under the same name gets a FRESH listener
                with self._lock:
                    self._thread = None
                with _listeners_lock:
                    for k, v in list(_listeners.items()):
                        if v is self:
                            del _listeners[k]
                return
            if out.get("replicas") is not None:
                version = out["version"]
                for h in self._live_handles():
                    h._apply_membership(list(out["replicas"]), version)
            elif out.get("backoff"):
                # controller long-poll slots saturated: don't hot-loop
                time.sleep(0.5)

    def healthy(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and time.monotonic() - self.last_ok < 90.0)


_listeners: dict = {}
_listeners_lock = threading.Lock()


def _shared_listener(controller, name: str) -> _SharedListener:
    key = (getattr(controller, "_actor_id", None) or id(controller),
           name)
    with _listeners_lock:
        lis = _listeners.get(key)
        if lis is None:
            lis = _SharedListener(controller, name)
            _listeners[key] = lis
        return lis


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._version = 0
        self._inflight: Dict[Any, int] = {}
        #: (ref, replica) of requests whose completion hasn't been
        #: observed yet — reaped (decrementing _inflight) on every route.
        self._outstanding: List = []
        self._fetched_at = 0.0
        self._listener: Optional[_SharedListener] = None
        #: serializes membership swaps (listener thread) against the
        #: routing counters (request thread)
        self._route_lock = threading.Lock()
        #: power-of-two-choices sampling: seeded per deployment so a
        #: replayed request sequence routes identically run to run
        #: (the process-global `random` module would not)
        self._rng = random.Random(zlib.crc32(deployment_name.encode()))
        self._closed = False

    # -- membership -------------------------------------------------------

    def _ensure_listener(self) -> None:
        if self._listener is not None and self._listener.healthy():
            return
        self._listener = _shared_listener(self._controller,
                                          self.deployment_name)
        self._listener.register(self)

    def close(self) -> None:
        """Detach from the long-poll listener (idempotent)."""
        self._closed = True

    def _apply_membership(self, replicas: List, version: int) -> None:
        # Reset counters on membership change (a freshly restarted
        # replica must not inherit stale load) and drop the matching
        # outstanding entries so they can't decrement the fresh counters.
        with self._route_lock:
            self._replicas = replicas
            self._version = version
            self._inflight = {r: 0 for r in replicas}
            self._outstanding = []
            self._fetched_at = time.monotonic()

    def _refresh(self, force: bool = False) -> None:
        import ray_tpu

        self._ensure_listener()
        if not force and self._replicas and \
                time.monotonic() - self._fetched_at < _REFRESH_S:
            return
        if not force and self._replicas and \
                self._listener is not None and self._listener.healthy():
            return  # live listener keeps us fresh; no poll needed
        # fallback poll: no listener heartbeat (wedged thread, deleted+
        # redeployed deployment) — _REFRESH_S staleness bound applies
        self._apply_membership(ray_tpu.get(
            self._controller.get_replicas.remote(self.deployment_name),
            timeout=30), self._version)

    def _reap(self) -> None:
        """Decrement in-flight counts for completed requests (the router
        equivalent of the reference's completion callback decrementing
        num_queued_queries, _private/router.py:261)."""
        from ray_tpu._private import worker_context

        cw = worker_context.maybe_core_worker()
        if cw is None or not self._outstanding:
            return
        still = []
        for ref, replica in self._outstanding:
            try:
                done = cw.is_ready(ref._info)
            except Exception:  # noqa: BLE001 - store closing
                done = True
            if done:
                self._inflight[replica] = max(
                    0, self._inflight.get(replica, 0) - 1)
            else:
                still.append((ref, replica))
        self._outstanding = still

    def _pick(self):
        if not self._replicas:
            raise RuntimeError(
                f"deployment {self.deployment_name!r} has no replicas")
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = self._rng.sample(self._replicas, 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) \
            else b

    def remote(self, *args, _serve_method: str = "__call__", **kwargs):
        """Route one request; returns an ObjectRef."""
        self._refresh()
        with self._route_lock:
            self._reap()
            replica = self._pick()
            self._inflight[replica] = self._inflight.get(replica, 0) + 1
        ref = replica.handle_request.remote(
            *args, _serve_method=_serve_method, **kwargs)
        with self._route_lock:
            self._outstanding.append((ref, replica))
        return ref

    def queue_len(self) -> int:
        """Unfinished requests routed through this handle (autoscaling
        signal)."""
        with self._route_lock:
            self._reap()
            return sum(self._inflight.values())

    def call(self, *args, timeout: float = 60.0, **kwargs):
        """Convenience: route + block for the result, with one retry
        through a table refresh if the replica died."""
        import ray_tpu

        try:
            return ray_tpu.get(self.remote(*args, **kwargs),
                               timeout=timeout)
        except Exception:  # noqa: BLE001 - replica may be gone; retry once
            self._refresh(force=True)
            return ray_tpu.get(self.remote(*args, **kwargs),
                               timeout=timeout)

    def method(self, name: str) -> "_MethodCaller":
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,
                                   self._controller))


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle.remote(*args, _serve_method=self._method,
                                   **kwargs)
