"""DeploymentHandle: client-side router to a deployment's replicas.

Reference analog: serve/handle.py:77 RayServeHandle +
_private/router.py:261 Router (:298 assign_request).  Routing is
least-loaded-of-two (power of two choices by in-flight count tracked
locally), with replica-list refresh from the controller on failure or
staleness.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

_REFRESH_S = 5.0


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._inflight: Dict[Any, int] = {}
        #: (ref, replica) of requests whose completion hasn't been
        #: observed yet — reaped (decrementing _inflight) on every route.
        self._outstanding: List = []
        self._fetched_at = 0.0

    def _refresh(self, force: bool = False) -> None:
        import ray_tpu

        if not force and self._replicas and \
                time.monotonic() - self._fetched_at < _REFRESH_S:
            return
        self._replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self.deployment_name),
            timeout=30)
        # Reset counters on membership refresh (a freshly restarted
        # replica must not inherit stale load) and drop the matching
        # outstanding entries so they can't decrement the fresh counters.
        self._inflight = {r: 0 for r in self._replicas}
        self._outstanding = []
        self._fetched_at = time.monotonic()

    def _reap(self) -> None:
        """Decrement in-flight counts for completed requests (the router
        equivalent of the reference's completion callback decrementing
        num_queued_queries, _private/router.py:261)."""
        from ray_tpu._private import worker_context

        cw = worker_context.maybe_core_worker()
        if cw is None or not self._outstanding:
            return
        still = []
        for ref, replica in self._outstanding:
            try:
                done = cw.is_ready(ref._info)
            except Exception:  # noqa: BLE001 - store closing
                done = True
            if done:
                self._inflight[replica] = max(
                    0, self._inflight.get(replica, 0) - 1)
            else:
                still.append((ref, replica))
        self._outstanding = still

    def _pick(self):
        if not self._replicas:
            raise RuntimeError(
                f"deployment {self.deployment_name!r} has no replicas")
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = random.sample(self._replicas, 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) \
            else b

    def remote(self, *args, _serve_method: str = "__call__", **kwargs):
        """Route one request; returns an ObjectRef."""
        self._refresh()
        self._reap()
        replica = self._pick()
        self._inflight[replica] = self._inflight.get(replica, 0) + 1
        ref = replica.handle_request.remote(
            *args, _serve_method=_serve_method, **kwargs)
        self._outstanding.append((ref, replica))
        return ref

    def queue_len(self) -> int:
        """Unfinished requests routed through this handle (autoscaling
        signal)."""
        self._reap()
        return sum(self._inflight.values())

    def call(self, *args, timeout: float = 60.0, **kwargs):
        """Convenience: route + block for the result, with one retry
        through a table refresh if the replica died."""
        import ray_tpu

        try:
            return ray_tpu.get(self.remote(*args, **kwargs),
                               timeout=timeout)
        except Exception:  # noqa: BLE001 - replica may be gone; retry once
            self._refresh(force=True)
            return ray_tpu.get(self.remote(*args, **kwargs),
                               timeout=timeout)

    def method(self, name: str) -> "_MethodCaller":
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,
                                   self._controller))


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle.remote(*args, _serve_method=self._method,
                                   **kwargs)
