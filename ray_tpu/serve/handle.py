"""DeploymentHandle: client-side router to a deployment's replicas.

Reference analog: serve/handle.py:77 RayServeHandle +
_private/router.py:261 Router (:298 assign_request).  Routing is
least-loaded-of-two (power of two choices by in-flight count tracked
locally), with replica-list refresh from the controller on failure or
staleness.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

_REFRESH_S = 5.0


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller):
        self.deployment_name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._inflight: Dict[Any, int] = {}
        self._fetched_at = 0.0

    def _refresh(self, force: bool = False) -> None:
        import ray_tpu

        if not force and self._replicas and \
                time.monotonic() - self._fetched_at < _REFRESH_S:
            return
        self._replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self.deployment_name),
            timeout=30)
        # reset the load counters each refresh window: they approximate
        # RECENT load for the power-of-two picker, not lifetime totals
        # (which would flood any freshly restarted replica)
        self._inflight = {r: 0 for r in self._replicas}
        self._fetched_at = time.monotonic()

    def _pick(self):
        if not self._replicas:
            raise RuntimeError(
                f"deployment {self.deployment_name!r} has no replicas")
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = random.sample(self._replicas, 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) \
            else b

    def remote(self, *args, _serve_method: str = "__call__", **kwargs):
        """Route one request; returns an ObjectRef."""
        self._refresh()
        replica = self._pick()
        self._inflight[replica] = self._inflight.get(replica, 0) + 1
        ref = replica.handle_request.remote(
            *args, _serve_method=_serve_method, **kwargs)
        # in-flight decay: without completion callbacks, age counts down
        # on the next refresh (coarse but keeps the picker balanced)
        return ref

    def call(self, *args, timeout: float = 60.0, **kwargs):
        """Convenience: route + block for the result, with one retry
        through a table refresh if the replica died."""
        import ray_tpu

        try:
            return ray_tpu.get(self.remote(*args, **kwargs),
                               timeout=timeout)
        except Exception:  # noqa: BLE001 - replica may be gone; retry once
            self._refresh(force=True)
            return ray_tpu.get(self.remote(*args, **kwargs),
                               timeout=timeout)

    def method(self, name: str) -> "_MethodCaller":
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,
                                   self._controller))


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle.remote(*args, _serve_method=self._method,
                                   **kwargs)
