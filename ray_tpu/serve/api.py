"""Public serve API (reference analog: serve/api.py:251-277
@serve.deployment, :455 serve.run)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Union

import cloudpickle

from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.handle import DeploymentHandle

_PROXY_NAME = "SERVE_HTTP_PROXY"


@dataclasses.dataclass
class Deployment:
    func_or_class: Union[Callable, type]
    name: str
    num_replicas: int = 1
    ray_actor_options: Optional[Dict[str, Any]] = None
    max_concurrent_queries: int = 8
    autoscaling_config: Optional[Dict[str, Any]] = None
    route_prefix: Optional[str] = None
    init_args: tuple = ()
    init_kwargs: Optional[Dict[str, Any]] = None

    def bind(self, *args, **kwargs) -> "Deployment":
        return dataclasses.replace(self, init_args=args,
                                   init_kwargs=kwargs)

    def options(self, **kwargs) -> "Deployment":
        return dataclasses.replace(self, **kwargs)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               max_concurrent_queries: int = 8,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               route_prefix: Optional[str] = None):
    """@serve.deployment decorator.  autoscaling_config (reference:
    serve autoscaling, _private/autoscaling_policy.py): dict with
    min_replicas / max_replicas / target_ongoing_requests /
    upscale_delay_s / downscale_delay_s — replica count then tracks
    queue depth instead of num_replicas."""

    def wrap(target):
        return Deployment(
            target, name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options,
            max_concurrent_queries=max_concurrent_queries,
            autoscaling_config=autoscaling_config,
            route_prefix=route_prefix)

    return wrap(_func_or_class) if _func_or_class is not None else wrap


def _get_or_create_controller():
    import ray_tpu

    ray_tpu._auto_init()
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:  # noqa: BLE001 - not created yet
        # concurrency sized for long-poll: every PROCESS parks one
        # listen_for_change call per deployment on a controller slot
        # (handle._SharedListener; parked calls wait on a Condition —
        # threads, not CPU)
        return ray_tpu.remote(num_cpus=0.1, lifetime="detached",
                              name=CONTROLLER_NAME, max_concurrency=128)(
            ServeController).remote()


def _graphify(obj, deployed: set, controller, overrides=None):
    """Deployment-graph support (reference: serve/deployment_graph.py on
    Ray DAG): bound deployments nested in init args deploy first and are
    replaced by handle markers the replica resolves at construction."""
    from ray_tpu.serve.replica import DeploymentHandleMarker

    if isinstance(obj, Deployment):
        _deploy_one(obj, deployed, controller, overrides=overrides)
        return DeploymentHandleMarker(obj.name)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_graphify(x, deployed, controller, overrides)
                         for x in obj)
    if isinstance(obj, dict):
        return {k: _graphify(v, deployed, controller, overrides)
                for k, v in obj.items()}
    return obj


def _deploy_one(target: Deployment, deployed: set, controller,
                route_prefix: Optional[str] = None,
                overrides=None) -> None:
    import ray_tpu

    if target.name in deployed:
        return
    deployed.add(target.name)
    ov = (overrides or {}).get(target.name)
    if ov:
        target = target.options(**ov)
    init_args = _graphify(target.init_args, deployed, controller,
                          overrides)
    init_kwargs = _graphify(target.init_kwargs or {}, deployed,
                            controller, overrides)
    ray_tpu.get(controller.deploy.remote(
        target.name, cloudpickle.dumps(target.func_or_class),
        init_args, init_kwargs,
        num_replicas=target.num_replicas,
        ray_actor_options=target.ray_actor_options,
        max_concurrent_queries=target.max_concurrent_queries,
        autoscaling_config=target.autoscaling_config,
        route_prefix=route_prefix or target.route_prefix), timeout=120)


def run(target: Deployment, *, route_prefix: Optional[str] = None,
        http: bool = False, http_port: int = 8000,
        _overrides: Optional[Dict[str, Dict[str, Any]]] = None
        ) -> DeploymentHandle:
    """Deploy (a graph of) deployments and return the root handle
    (reference serve.run, serve/api.py:455; graphs via .bind()
    composition as in serve/deployment_graph.py).  With http=True an
    aiohttp ingress proxy is started as well.  ``_overrides`` (the
    declarative-config path, serve/schema.py): per-deployment option
    overlays applied to EVERY deployment in the graph by name."""
    controller = _get_or_create_controller()
    # config-over-code precedence: a declarative route_prefix override
    # on the root deployment wins over the code-level default
    root_ov = (_overrides or {}).get(target.name) or {}
    # route_prefix always defaults to /<name> (reference semantics) so
    # a proxy started later — e.g. the per-node fleet — can route to
    # deployments created before it
    prefix = route_prefix or root_ov.get("route_prefix") or \
        target.route_prefix or f"/{target.name}"
    deployed: set = set()
    _deploy_one(target, deployed, controller, route_prefix=prefix,
                overrides=_overrides)
    if _overrides:
        unmatched = set(_overrides) - deployed
        if unmatched:
            raise ValueError(
                f"config deployments {sorted(unmatched)} matched no "
                f"deployment in the graph (deployed: {sorted(deployed)})")
    if http:
        start_http_proxy(port=http_port)
    return DeploymentHandle(target.name, controller)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_or_create_controller())


def start_http_proxy(port: int = 8000, host: str = "127.0.0.1",
                     per_node: bool = False) -> str:
    """Start the HTTP ingress.  ``per_node=True`` starts one proxy actor
    on EVERY alive node, pinned via each node's affinity resource
    (reference: serve/_private/http_state.py HTTPProxyStateManager —
    one proxy per node, node:<ip> affinity), so ingress scales
    horizontally with the cluster and requests enter on the node they
    hit.  Returns the local (first) proxy's address."""
    import ray_tpu
    from ray_tpu.serve.http_proxy import HTTPProxyActor

    controller = _get_or_create_controller()
    if not per_node:
        try:
            proxy = ray_tpu.get_actor(_PROXY_NAME)
        except Exception:  # noqa: BLE001
            proxy = ray_tpu.remote(num_cpus=0.1, lifetime="detached",
                                   name=_PROXY_NAME)(HTTPProxyActor).remote(
                controller, host, port)
        ray_tpu.get(proxy.ping.remote(), timeout=60)
        return ray_tpu.get(proxy.address.remote(), timeout=30)

    proxies = []
    for i, node in enumerate(n for n in ray_tpu.nodes() if n["Alive"]):
        node_hex = node["NodeID"]
        name = f"{_PROXY_NAME}:{node_hex[:12]}"
        try:
            proxy = ray_tpu.get_actor(name)
        except Exception:  # noqa: BLE001
            proxy = ray_tpu.remote(
                num_cpus=0.1, lifetime="detached", name=name,
                resources={f"node:{node_hex}": 0.01},
            )(HTTPProxyActor).remote(controller, host, port + i)
        proxies.append(proxy)
    addrs = ray_tpu.get([p.address.remote() for p in proxies],
                        timeout=60)
    ray_tpu.get([p.ping.remote() for p in proxies], timeout=60)
    return addrs[0]


def status() -> dict:
    """Cluster serve status: {deployment: {status, replicas, ...}}
    (reference: serve.status())."""
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=30.0)


def engine_stats(deployment_name: str, timeout: float = 30.0) -> dict:
    """Engine telemetry snapshot from one replica of an LM deployment
    (p50/p95/p99 TTFT + queue wait, throughput, slot utilization —
    serve/telemetry.py).  Raises for deployments without an
    ``engine_stats`` method; the dashboard's ``/api/serve/stats``
    aggregates this across every deployment, skipping those."""
    import ray_tpu

    handle = get_deployment_handle(deployment_name)
    return ray_tpu.get(handle.method("engine_stats").remote(),
                       timeout=timeout)


def delete(name: str) -> None:
    import ray_tpu

    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)


def shutdown() -> None:
    import ray_tpu

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote(), timeout=60)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001
        pass
    try:
        proxy = ray_tpu.get_actor(_PROXY_NAME)
        ray_tpu.kill(proxy)
    except Exception:  # noqa: BLE001
        pass
