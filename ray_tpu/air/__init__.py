"""Shared ML plumbing (reference analog: python/ray/air/).

`Checkpoint` (dict ⇄ directory ⇄ object-store interconvertible artifact),
`session` (worker-side report/context API), and the config dataclasses
consumed by Train/Tune (`ScalingConfig`, `RunConfig`, `FailureConfig`,
`CheckpointConfig`).
"""

from ray_tpu.air.batch_predictor import BatchPredictor, Predictor
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.air import session

__all__ = ["Checkpoint", "BatchPredictor", "Predictor", "ScalingConfig", "RunConfig", "FailureConfig",
           "CheckpointConfig", "Result", "session"]
