"""Worker-side training session API.

User train loops call ``session.report(metrics, checkpoint=...)`` /
``session.get_checkpoint()`` / ``session.get_world_rank()`` etc.
(reference analog: air/session.py:12 report, :241 get_dataset_shard;
backed by train/_internal/session.py:58 _TrainSession).  The active
session is process-global, installed by the train worker before running
the user loop.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_session_lock = threading.Lock()
_active_session = None


def _set_session(s) -> None:
    global _active_session
    with _session_lock:
        _active_session = s


def _get_session():
    if _active_session is None:
        raise RuntimeError(
            "no active training session; session.* APIs are only valid "
            "inside a train loop launched by a Trainer")
    return _active_session


def report(metrics: Dict[str, Any], *, checkpoint=None) -> None:
    """Ship metrics (+ optional Checkpoint) to the trial driver; blocks
    until consumed so workers stay in lockstep with the driver loop."""
    _get_session().report(metrics, checkpoint=checkpoint)


def get_checkpoint():
    """Latest committed Checkpoint (for resume-from-failure), or None."""
    return _get_session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    """This worker's shard of the dataset registered with the Trainer."""
    return _get_session().get_dataset_shard(name)


def get_world_size() -> int:
    return _get_session().world_size


def get_world_rank() -> int:
    return _get_session().world_rank


def get_local_rank() -> int:
    return _get_session().local_rank


def get_trial_name() -> str:
    return _get_session().trial_name


def get_trial_id() -> str:
    return _get_session().trial_id


def get_config() -> Dict[str, Any]:
    """The train_loop_config / trial config for this run."""
    return _get_session().config
