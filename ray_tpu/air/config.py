"""Run/scaling configuration dataclasses.

Reference analogs: air/config.py:79 ScalingConfig, :640 RunConfig,
:452 FailureConfig, :511 CheckpointConfig.  TPU-first deltas: the worker
resource is ``num_tpus`` (the "TPU" predefined resource), and ScalingConfig
carries an optional ``topology`` (e.g. "v5e-8") plus a ``mesh`` spec so
trainers can build slice-aware meshes instead of flat process groups.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Union


@dataclasses.dataclass
class ScalingConfig:
    """How many workers, what each gets, and how devices form a mesh."""

    num_workers: int = 1
    use_tpu: bool = False
    num_cpus_per_worker: float = 1.0
    num_tpus_per_worker: float = 0.0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None     # e.g. "v5e-8": reserve an ICI domain
    mesh: Optional[Any] = None         # ray_tpu.parallel.MeshSpec override

    def __post_init__(self):
        if self.use_tpu and not self.num_tpus_per_worker:
            self.num_tpus_per_worker = 1.0

    @property
    def _trainer_resources(self) -> Dict[str, float]:
        res: Dict[str, float] = {"CPU": float(self.num_cpus_per_worker)}
        if self.num_tpus_per_worker:
            res["TPU"] = float(self.num_tpus_per_worker)
        for k, v in (self.resources_per_worker or {}).items():
            res[k] = float(v)
        return res

    def as_placement_group_bundles(self):
        return [dict(self._trainer_resources)
                for _ in range(self.num_workers)]


@dataclasses.dataclass
class FailureConfig:
    """max_failures: -1 = unlimited restarts, 0 = fail fast (reference
    air/config.py:452)."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """Retention policy for checkpoints (reference air/config.py:511)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclasses.dataclass
class RunConfig:
    """Experiment-level config (reference air/config.py:640)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Union[Dict[str, Any], int]] = None
    verbose: int = 1
    #: tune.Callback instances fired on trial lifecycle events; when
    #: None, Tuner attaches the default CSV/JSON/TensorBoard loggers
    #: (reference air/config.py RunConfig.callbacks + DEFAULT_LOGGERS).
    callbacks: Optional[list] = None
    #: remote URI (kv:// / s3:// / mem://, via the Data filesystem seam)
    #: the experiment directory syncs to — experiment state + per-trial
    #: artifacts upload on every throttled experiment checkpoint, so a
    #: lost head can Tuner.restore from the remote copy (reference:
    #: tune/syncer.py SyncConfig cloud upload).
    sync_to: Optional[str] = None
