"""BatchPredictor: checkpoint → parallel batch inference over a Dataset.

Reference analog: python/ray/train/batch_predictor.py (BatchPredictor
.from_checkpoint + .predict over a Dataset with an actor pool).  The
predictor class is constructed ONCE per pool actor from the checkpoint
— model weights load per actor, not per batch — and prediction runs as
a normal dataset stage, so it composes with the rest of the data
pipeline (the reference's GPU batch-prediction benchmark shape,
doc/source/ray-air/benchmarks.rst:119).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """User-facing base: subclass with from_checkpoint + predict
    (reference: ray.train.predictor.Predictor)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError


class BatchPredictor:
    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor]):
        self._checkpoint_data = checkpoint.to_dict()
        self._predictor_cls = predictor_cls

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor]
                        ) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls)

    def predict(self, dataset, *, batch_format: str = "numpy",
                compute=None, min_scoring_workers: int = 1,
                max_scoring_workers: Optional[int] = None,
                num_cpus_per_worker: float = 1.0):
        """Run the predictor over every batch of `dataset`; returns a new
        Dataset of predictions.  Uses an actor pool (weights load once
        per actor); size it with min/max_scoring_workers or pass an
        explicit ActorPoolStrategy via `compute`."""
        from ray_tpu.data import ActorPoolStrategy

        ckpt_data = self._checkpoint_data
        predictor_cls = self._predictor_cls

        class _Scorer:
            def __init__(self):
                self._p = predictor_cls.from_checkpoint(
                    Checkpoint.from_dict(ckpt_data))

            def __call__(self, batch):
                return self._p.predict(batch)

        if compute is None:
            size = max(min_scoring_workers,
                       max_scoring_workers or min_scoring_workers)
            compute = ActorPoolStrategy(size=size,
                                        num_cpus=num_cpus_per_worker)
        return dataset.map_batches(_Scorer, batch_format=batch_format,
                                   compute=compute)
