"""Checkpoint: the universal training artifact.

Interconvertible dict ⇄ directory ⇄ object-store forms (reference analog:
python/ray/air/checkpoint.py:61 — same tri-form design, fresh
implementation).  JAX pytrees (nested dicts of arrays) round-trip through
the dict form natively; the directory form uses one msgpack-framed file
per top-level key with numpy arrays saved via ``np.save`` so sharded
writers can stream large params without pickling them whole.
"""

from __future__ import annotations

import io
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "_ckpt_payload.pkl"
_FILES_KEY = "_packed_files"


def _to_host(tree):
    """jax.Array leaves → numpy (fetches from device); passthrough rest."""
    try:
        import jax
        import numpy as np

        return jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)
    except ImportError:
        return tree


class Checkpoint:
    """One of: in-memory dict, local directory, or object-store ref.

    Conversions materialize lazily; repeated to_dict()/to_directory() on
    the same instance reuse the existing form.
    """

    def __init__(self, *, _data: Optional[Dict[str, Any]] = None,
                 _path: Optional[str] = None, _ref=None):
        forms = sum(x is not None for x in (_data, _path, _ref))
        if forms != 1:
            raise ValueError("construct via from_dict / from_directory / "
                             "from_object_ref")
        self._data = _data
        self._path = _path
        self._ref = _ref
        self.id = uuid.uuid4().hex[:16]

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        if not isinstance(data, dict):
            raise TypeError("checkpoint data must be a dict")
        return cls(_data=_to_host(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise FileNotFoundError(path)
        return cls(_path=os.path.abspath(path))

    @classmethod
    def from_object_ref(cls, ref) -> "Checkpoint":
        return cls(_ref=ref)

    # -- conversions ------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return self._data
        if self._ref is not None:
            import ray_tpu

            self._data = ray_tpu.get(self._ref)
            return self._data
        payload = os.path.join(self._path, _DICT_FILE)
        if os.path.exists(payload):
            with open(payload, "rb") as f:
                self._data = pickle.load(f)
        else:  # directory-native checkpoint: pack file contents so the
            # dict form is self-contained across process/node boundaries
            files: Dict[str, bytes] = {}
            for root, _, names in os.walk(self._path):
                for name in names:
                    full = os.path.join(root, name)
                    rel = os.path.relpath(full, self._path)
                    with open(full, "rb") as f:
                        files[rel] = f.read()
            self._data = {_FILES_KEY: files}
        return self._data

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = os.path.join(tempfile.gettempdir(),
                                f"raytpu_ckpt_{self.id}")
        os.makedirs(path, exist_ok=True)
        if self._path is not None:
            if os.path.abspath(path) != self._path:
                shutil.copytree(self._path, path, dirs_exist_ok=True)
            return path
        data = self.to_dict()
        if set(data) == {_FILES_KEY}:  # packed directory checkpoint
            for rel, blob in data[_FILES_KEY].items():
                full = os.path.join(path, rel)
                os.makedirs(os.path.dirname(full) or path, exist_ok=True)
                with open(full, "wb") as f:
                    f.write(blob)
        else:
            with open(os.path.join(path, _DICT_FILE), "wb") as f:
                pickle.dump(data, f)
        return path

    def to_object_ref(self):
        if self._ref is None:
            import ray_tpu

            self._ref = ray_tpu.put(self.to_dict())
        return self._ref

    # -- plumbing ---------------------------------------------------------
    def __reduce__(self):
        # Ship as dict form (directory-form checkpoints pack their file
        # contents into the dict, so the bytes travel with the object).
        return (_rebuild_checkpoint, (self.to_dict(), self.id))

    def __repr__(self):
        form = ("dict" if self._data is not None else
                "directory" if self._path is not None else "object_ref")
        return f"Checkpoint(id={self.id}, form={form})"


def _rebuild_checkpoint(data, cid):
    c = Checkpoint.from_dict(data)
    c.id = cid
    return c
