"""@remote functions (reference analog: python/ray/remote_function.py)."""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu._private import worker_context
from ray_tpu._private.worker_context import ObjectRef

_DEFAULT_TASK_RESOURCES = {"CPU": 1.0}


def _build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    resources: Dict[str, float] = {}
    num_cpus = opts.get("num_cpus")
    num_tpus = opts.get("num_tpus")
    num_gpus = opts.get("num_gpus")  # accepted for API parity; maps to TPU-less
    resources["CPU"] = float(num_cpus if num_cpus is not None else 1.0)
    if num_tpus:
        resources["TPU"] = float(num_tpus)
    if num_gpus:
        resources["GPU"] = float(num_gpus)
    for k, v in (opts.get("resources") or {}).items():
        resources[k] = float(v)
    if opts.get("memory"):
        resources["memory"] = float(opts["memory"])
    # TPU chips are exclusive per process under libtpu: a worker is handed
    # whole chips via TPU_VISIBLE_CHIPS, so fractional grants would pass
    # ResourceSet admission but fail at worker start (ADVICE r1).
    tpu = resources.get("TPU")
    if tpu is not None and tpu != int(tpu):
        raise ValueError(
            f"num_tpus must be a whole number of chips (got {tpu}): TPU "
            f"chips are dedicated per worker process under libtpu and "
            f"cannot be fractionally shared the way CPUs can.")
    return resources


def _pg_option(opts: Dict[str, Any]) -> Optional[Tuple[bytes, int]]:
    strategy = opts.get("scheduling_strategy")
    pg = opts.get("placement_group")
    index = opts.get("placement_group_bundle_index", -1)
    if strategy is not None and hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        index = getattr(strategy, "placement_group_bundle_index", -1) or -1
    if pg is None:
        return None
    pg_id = pg.id.binary() if hasattr(pg, "id") else pg
    return (pg_id, index if index is not None and index >= 0 else 0)


class RemoteFunction:
    """Wrapper created by ``@ray_tpu.remote`` on a function.

    (Reference: python/ray/remote_function.py RemoteFunction._remote.)
    """

    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._function = fn
        self._options = dict(options or {})
        self._fid: Optional[bytes] = None
        self._pickled: Optional[bytes] = None
        self._export_lock = threading.Lock()
        self.__name__ = getattr(fn, "__name__", "remote_function")
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__}() cannot be called directly; "
            f"use {self.__name__}.remote().")

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(opts)
        rf = RemoteFunction(self._function, merged)
        rf._pickled = self._pickled
        return rf

    def bind(self, *args, **kwargs):
        """Record a lazy DAG node instead of submitting (reference:
        ray.dag — fn.bind builds a FunctionNode)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __reduce__(self):
        # Remote functions captured in closures of other remote functions
        # must travel; rebuild fresh (locks are per-process).
        return (RemoteFunction, (self._function, self._options))

    def _ensure_exported(self, cw) -> bytes:
        with self._export_lock:
            if self._pickled is None:
                self._pickled = cloudpickle.dumps(self._function)
        # Re-export per core-worker (cheap: content-addressed by sha1).
        return cw.export_function(self._pickled)

    def remote(self, *args, **kwargs):
        from ray_tpu import _auto_init

        _auto_init()
        cw = worker_context.core_worker()
        fid = self._ensure_exported(cw)
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        if num_returns == "dynamic":
            # generator task: the count of returns is decided by the
            # task at run time (reference: num_returns="dynamic" /
            # ObjectRefGenerator).  get() on the returned ref yields the
            # list of per-item ObjectRefs.
            num_returns = -1
        refs = cw.submit_task(
            fid, args, kwargs,
            num_returns=num_returns,
            resources=_build_resources(opts),
            name=opts.get("name") or self.__name__,
            max_retries=opts.get("max_retries", 3),
            pg=_pg_option(opts),
        )
        wrapped = [ObjectRef(r) for r in refs]
        if num_returns == 0:
            return None
        if num_returns in (1, -1):
            return wrapped[0]
        return wrapped
