"""JAX backend: the TPU-native replacement for the reference's Torch/NCCL
backend (train/torch/config.py:29 TorchConfig, :70
_setup_torch_process_group).

Where the reference calls ``dist.init_process_group(nccl)`` and lets DDP
allreduce gradients over NCCL, the JAX backend has three modes:

  "jax"   — multi-host SPMD: pick rank 0's host as coordinator, call
            ``jax.distributed.initialize(coordinator, n, rank)`` on every
            worker; each worker then sees the global TPU mesh and the
            train step's psum rides ICI inside jit.  (The TPU analog of
            the NCCL ring — but compiled into the program by XLA.)
  "store" — object-store collective group (ray_tpu.parallel.collective):
            gradients allreduce through shared memory.  Works anywhere
            (CPU tests, heterogeneous hosts); this is the
            ray.util.collective-parity path.
  "none"  — workers are independent (each jits over its own local
            devices; user syncs manually).

"auto" picks "jax" when workers hold TPU resources, else "store".
"""

from __future__ import annotations

import dataclasses
import socket
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    distributed: str = "auto"           # "auto"|"jax"|"store"|"none"
    coordinator_port: int = 0           # 0 = pick a free port
    virtual_devices: Optional[int] = None  # per-worker fake CPU devices
    group_name: str = "train"

    @property
    def backend_cls(self):
        return JaxBackend


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _setup_virtual_devices(n: int):
    """Give this worker n virtual CPU jax devices (test mode; the analog
    of the reference's _fake_gpus)."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if f"--xla_force_host_platform_device_count={n}" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend may be committed already
        pass


def _setup_jax_distributed(coordinator: str, num_processes: int,
                           process_id: int):
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def _setup_store_group(world_size: int, rank: int, group_name: str):
    from ray_tpu.parallel import collective

    collective.init_collective_group(world_size, rank,
                                     group_name=group_name)


def _get_node_ip() -> str:
    return socket.gethostbyname(socket.gethostname())


class JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        cfg = backend_config
        n = len(worker_group)

        if cfg.virtual_devices:
            worker_group.execute(_setup_virtual_devices,
                                 cfg.virtual_devices)

        mode = cfg.distributed
        if mode == "auto":
            # decide by what THESE workers were granted, not cluster totals
            worker_tpu = getattr(worker_group, "resources_per_worker",
                                 {}).get("TPU", 0)
            mode = "jax" if worker_tpu and n > 1 else \
                ("store" if n > 1 else "none")
        self.mode = mode

        if mode == "jax" and n > 1:
            ip = worker_group.execute_single(0, _get_node_ip)
            port = cfg.coordinator_port or \
                worker_group.execute_single(0, _pick_port)
            coordinator = f"{ip}:{port}"
            import ray_tpu

            ray_tpu.get([w.execute.remote(_setup_jax_distributed,
                                          coordinator, n, i)
                         for i, w in enumerate(worker_group.workers)],
                        timeout=120)
        elif mode == "store" and n > 1:
            import ray_tpu

            ray_tpu.get([w.execute.remote(_setup_store_group, n, i,
                                          cfg.group_name)
                         for i, w in enumerate(worker_group.workers)],
                        timeout=120)

    def on_shutdown(self, worker_group, backend_config: JaxConfig) -> None:
        def _teardown(group_name):
            from ray_tpu.parallel import collective

            if collective.is_group_initialized(group_name):
                collective.destroy_collective_group(group_name)

        try:
            worker_group.execute(_teardown, backend_config.group_name)
        except Exception:  # noqa: BLE001 - workers may be dead
            pass


def allreduce_gradients(grads, *, op: str = "mean",
                        group_name: str = "train"):
    """Allreduce a gradient pytree across the train worker group (store
    mode).  On a real multi-host mesh, use psum inside your jitted step
    instead — this helper is the CPU/heterogeneous path."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel import collective

    leaves, treedef = jax.tree.flatten(grads)
    reduced = [jnp.asarray(collective.allreduce(leaf, op=op,
                                                group_name=group_name))
               for leaf in leaves]
    return jax.tree.unflatten(treedef, reduced)
