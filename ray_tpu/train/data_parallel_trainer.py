"""DataParallelTrainer (reference analog:
train/data_parallel_trainer.py:51, training_loop :324): run one
train_loop_per_worker function on N ranks via BackendExecutor, pump
reported results, keep the latest checkpoint, restart the gang on worker
failure up to FailureConfig.max_failures.
"""

from __future__ import annotations

import logging
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (FailureConfig, RunConfig, ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.backend_executor import (BackendExecutor,
                                            TrainingWorkerError)
from ray_tpu.train.base_trainer import BaseTrainer

logger = logging.getLogger(__name__)


class DataParallelTrainer(BaseTrainer):
    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = dict(train_loop_config or {})
        self.backend_config = backend_config or \
            type(self)._default_backend_config

    def _apply_trial_config(self, config: Dict[str, Any]) -> None:
        merged = dict(self.train_loop_config)
        merged.update(config)
        self.train_loop_config = merged

    def training_loop(self) -> Result:
        sc = self.scaling_config
        fc = (self.run_config.failure_config or FailureConfig())
        executor = BackendExecutor(
            self.backend_config,
            num_workers=sc.num_workers,
            resources_per_worker=sc._trainer_resources,
            max_restarts=fc.max_failures,
            placement_strategy=sc.placement_strategy)
        trial_id = uuid.uuid4().hex[:8]
        trial_name = self.run_config.name or \
            f"{type(self).__name__}_{trial_id}"

        history = []
        final_error: Optional[BaseException] = None
        checkpoint = self.resume_from_checkpoint
        executor.start()
        try:
            while True:
                try:
                    executor.start_training(
                        self.train_loop_per_worker,
                        config=self.train_loop_config,
                        datasets=self.datasets,
                        checkpoint=checkpoint,
                        trial_name=trial_name, trial_id=trial_id)
                    while True:
                        round_results = executor.fetch_next_result()
                        if round_results is None:
                            break
                        metrics = dict(round_results[0].metrics or {})
                        metrics["_round"] = len(history)
                        history.append(metrics)
                    break  # clean finish
                except TrainingWorkerError as e:
                    if fc.max_failures == 0:
                        final_error = e
                        break
                    checkpoint = executor.latest_checkpoint
                    try:
                        executor.restart()
                    except TrainingWorkerError as e2:
                        final_error = e2
                        break
        finally:
            latest = executor.latest_checkpoint
            executor.shutdown()

        return Result(metrics=history[-1] if history else None,
                      checkpoint=latest, error=final_error,
                      metrics_history=history)
