"""BackendExecutor: owns the worker group and the training lifecycle.

Reference analog: train/_internal/backend_executor.py:42 (:93 start,
:275 start_training) — create worker gang (placement-group PACK), run
the Backend's process-group setup, install per-rank sessions, launch the
user loop, and pump results; on worker failure tear down and restart the
whole gang (SPMD meshes can't lose a member — SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train._internal.session import TrainingResult
from ray_tpu.train._internal.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingWorkerError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig, *,
                 num_workers: int = 1,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 max_restarts: int = 0,
                 placement_strategy: str = "PACK"):
        self._config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._num_workers = num_workers
        self._resources = resources_per_worker or {"CPU": 1.0}
        self._max_restarts = max_restarts
        self._placement_strategy = placement_strategy
        self._restarts = 0
        self._pg = None
        self.worker_group: Optional[WorkerGroup] = None
        self.latest_checkpoint: Optional[Checkpoint] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        # Gang-reserve the whole worker set atomically so two concurrent
        # trainers can't each grab half a cluster and deadlock (reference
        # backend_executor.py:137-160 _create_placement_group).
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)

        if self._pg is None and self._num_workers > 1:
            pg = placement_group(
                [dict(self._resources) for _ in range(self._num_workers)],
                strategy=self._placement_strategy)
            try:
                pg.ready(timeout=120.0)
            except Exception:
                remove_placement_group(pg)
                raise
            self._pg = pg
        self.worker_group = WorkerGroup(self._num_workers, self._resources,
                                        placement_group=self._pg)
        self._backend.on_start(self.worker_group, self._config)

    def start_training(self, train_fn: Callable,
                       config: Optional[Dict[str, Any]] = None,
                       datasets: Optional[Dict[str, Any]] = None,
                       checkpoint: Optional[Checkpoint] = None,
                       trial_name: str = "", trial_id: str = "") -> None:
        assert self.worker_group is not None, "call start() first"
        if checkpoint is not None:
            self.latest_checkpoint = checkpoint
        shards = _shard_datasets(datasets or {}, self._num_workers)
        init_refs = []
        for rank, w in enumerate(self.worker_group.workers):
            init_refs.append(w.init_session.remote(
                world_rank=rank, local_rank=rank,
                world_size=self._num_workers,
                trial_name=trial_name, trial_id=trial_id,
                config=config or {},
                dataset_shards=shards[rank],
                checkpoint=self.latest_checkpoint))
        ray_tpu.get(init_refs, timeout=120)
        self._backend.on_training_start(self.worker_group, self._config)
        ray_tpu.get([w.start_training.remote(train_fn)
                     for w in self.worker_group.workers], timeout=120)

    def fetch_next_result(self) -> Optional[List[TrainingResult]]:
        """One lockstep round: next_result from every worker.

        Returns per-rank results for a "report" round, or None when all
        workers finished.  Raises TrainingWorkerError on worker failure.
        """
        assert self.worker_group is not None
        results = ray_tpu.get([w.next_result.remote()
                               for w in self.worker_group.workers],
                              timeout=600)
        types = {r.type for r in results}
        if "error" in types:
            errs = [r.error for r in results if r.type == "error"]
            tb = getattr(errs[0], "_train_traceback", "")
            raise TrainingWorkerError(
                f"training failed on a worker: {errs[0]!r}\n{tb}") \
                from errs[0]
        if types == {"done"}:
            return None
        if "done" in types:
            raise TrainingWorkerError(
                "workers out of sync: some finished while others "
                "reported (every rank must call session.report the same "
                "number of times)")
        ckpt = next((r.checkpoint for r in results
                     if r.checkpoint is not None), None)
        if ckpt is not None:
            self.latest_checkpoint = ckpt
        return results

    def restart(self) -> None:
        """Tear down and rebuild the gang (elastic recovery; reference
        backend_executor.py:512 _restart)."""
        self._restarts += 1
        if self._restarts > self._max_restarts >= 0:
            raise TrainingWorkerError(
                f"exceeded max_restarts={self._max_restarts}")
        logger.warning("restarting worker group (attempt %d/%d)",
                       self._restarts, self._max_restarts)
        self.shutdown()
        self.start()

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._config)
            except Exception:  # noqa: BLE001
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001
                pass
            self._pg = None


def _shard_datasets(datasets: Dict[str, Any],
                    num_workers: int) -> List[Dict[str, Any]]:
    """Split each dataset across workers.  A dataset may be: a Dataset
    (ray_tpu.data) — split via .split(); a list/array — strided slices;
    or a callable(rank, world) -> shard."""
    shards: List[Dict[str, Any]] = [{} for _ in range(num_workers)]
    for name, ds in datasets.items():
        if hasattr(ds, "split"):
            parts = ds.split(num_workers)
            for r in range(num_workers):
                shards[r][name] = parts[r]
        elif callable(ds):
            for r in range(num_workers):
                shards[r][name] = ds(r, num_workers)
        elif isinstance(ds, dict):  # dict of columns: stride each array
            for r in range(num_workers):
                shards[r][name] = {k: v[r::num_workers]
                                   for k, v in ds.items()}
        else:
            for r in range(num_workers):
                shards[r][name] = ds[r::num_workers] \
                    if hasattr(ds, "__getitem__") else ds
    return shards
