"""Gradient accumulation: fit a large effective batch in bounded HBM.

The standard TPU recipe when the wanted global batch exceeds device
memory at full activation size: split the batch into microbatches,
accumulate gradients across them inside ONE jitted step (a `lax.scan`,
so one dispatch and one optimizer update per effective batch), and
apply the update once.  Pairs with per-microbatch `jax.checkpoint`
already inside the models.

No reference analog at the framework level (torch users hand-roll
`loss.backward()` loops); here it's a first-class loop util because the
jit boundary placement (scan INSIDE the step) is the part people get
wrong — an outer Python loop would re-dispatch and re-transfer per
microbatch.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

__all__ = ["accumulated_train_step"]


def accumulated_train_step(loss_fn: Callable, tx, *,
                           num_microbatches: int,
                           telemetry: bool = False,
                           telemetry_name: str = "grad_accum",
                           jit_kwargs=None) -> Callable:
    """Build `step(params, opt_state, batch) -> (params, opt_state,
    loss)` that averages gradients over `num_microbatches` slices of the
    leading batch axis before applying ONE optimizer update.

    loss_fn(params, microbatch) -> scalar loss.  Every leaf of `batch`
    must have a leading axis divisible by num_microbatches.  By
    default the returned step is NOT jitted — wrap it in jax.jit (with
    your shardings) at the call site.

    telemetry=True closes the observability gap accumulated steps used
    to have (they bypassed ``instrument_train_step`` entirely, so
    their compiles and step times were invisible): the step is jitted
    HERE (pass ``jit_kwargs`` for shardings/donation) and wrapped with
    the same observatory + step-time + trainwatch anatomy stack as
    ``build_train_step``, under the ``train.step`` program name — an
    accumulated step IS the train step.  Read it back via
    ``train_stats(telemetry_name)``."""
    import jax
    import jax.numpy as jnp
    import optax

    n = num_microbatches

    def step(params, opt_state, batch) -> Tuple[Any, Any, jnp.ndarray]:
        def split(v):
            b = v.shape[0]
            if b % n:
                raise ValueError(
                    f"batch axis {b} not divisible by "
                    f"num_microbatches={n}")
            return v.reshape(n, b // n, *v.shape[1:])

        micro = jax.tree.map(split, batch)
        grad_fn = jax.value_and_grad(loss_fn)

        def body(carry, mb):
            gsum, lsum = carry
            loss, grads = grad_fn(params, mb)
            gsum = jax.tree.map(jnp.add, gsum, grads)
            return (gsum, lsum + loss), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (gsum, lsum), _ = jax.lax.scan(body, (zeros, jnp.float32(0.0)),
                                       micro)
        grads = jax.tree.map(lambda g: g / n, gsum)
        updates, new_opt = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_opt, lsum / n)

    if not telemetry:
        return step

    from ray_tpu._private.device_stats import get_registry
    from ray_tpu.train.goodput import (get_goodput_tracker,
                                       instrument_trainwatch)
    from ray_tpu.train.telemetry import (get_train_telemetry,
                                         instrument_train_step)

    jitted = jax.jit(step, **(jit_kwargs or {}))
    jitted = get_registry().instrument("train.step", jitted)
    jitted = instrument_train_step(
        jitted, telemetry=get_train_telemetry(telemetry_name))
    wrapped = instrument_trainwatch(
        jitted, tracker=get_goodput_tracker(telemetry_name))
    wrapped._raw_step = step
    return wrapped
