"""Sharded checkpointing for mesh-parallel training (orbax-backed).

SURVEY §7 lists "orbax-style sharded checkpoints" among the gaps the
reference leaves open (its Train checkpoints are whole-model torch
state_dicts shipped through the object store).  On TPU the params are
GSPMD-sharded jax.Arrays: every host must write exactly its own shards
(a gather-to-host-0 both OOMs and wastes ICI), and restore must be able
to RE-shard onto a different mesh (elastic restart onto fewer/more
chips, or a different parallelism layout).

orbax's OCDBT/zarr format does both; these helpers pin down the
framework's conventions (layout, resharding, AIR interop) so trainers
and user code share one path.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

__all__ = ["save_sharded", "restore_sharded", "latest_step",
           "sharded_checkpoint_to_air"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _step_dir(path: str, step: Optional[int]) -> str:
    return os.path.join(path, f"step_{step}") if step is not None else path


def _tree_bytes(tree: Any) -> int:
    """Total array bytes in a pytree (0 when leaves carry no nbytes)."""
    try:
        import jax

        return int(sum(getattr(x, "nbytes", 0) or 0
                       for x in jax.tree_util.tree_leaves(tree)))
    except Exception:  # noqa: BLE001 - never fail a checkpoint on this
        return 0


def _record_ckpt(kind: str, dur_s: float, nbytes: int, target: str,
                 step: Optional[int], trainer: str) -> None:
    """Book one save/restore pause into trainwatch: the goodput
    tracker (so checkpoint pauses show up in the goodput denominator
    and the next step's ``checkpoint`` anatomy leg) plus a
    ``ckpt_save``/``ckpt_restore`` flight-recorder journal event."""
    try:
        from ray_tpu.train.goodput import (get_goodput_tracker,
                                           get_train_recorder)

        get_goodput_tracker(trainer).record_checkpoint(
            kind, dur_s, nbytes=nbytes, step=step)
        get_train_recorder(trainer).record(
            f"ckpt_{kind}", step=step,
            dur_ms=round(dur_s * 1e3, 3), bytes=nbytes, path=target)
    except Exception:  # noqa: BLE001 - observability must not raise
        pass


def save_sharded(params: Any, path: str, *,
                 step: Optional[int] = None,
                 trainer: str = "default") -> str:
    """Write a (possibly mesh-sharded) pytree; each process writes only
    its addressable shards.  Returns the checkpoint directory.  The
    pause is timed and journaled under the named trainer's trainwatch
    state (``train_stats(trainer)["checkpoint"]``)."""
    target = os.path.abspath(_step_dir(path, step))
    ckptr = _checkpointer()
    t0 = time.perf_counter()
    ckptr.save(target, params, force=True)
    ckptr.wait_until_finished()
    _record_ckpt("save", time.perf_counter() - t0,
                 _tree_bytes(params), target, step, trainer)
    return target


def restore_sharded(path: str, *, step: Optional[int] = None,
                    template: Any = None, mesh=None, axes: Any = None,
                    rules=None, trainer: str = "default") -> Any:
    """Restore a pytree saved with save_sharded.

    Resharding: pass `mesh` + `axes` (the model's logical-axis pytree,
    e.g. gpt2_logical_axes(cfg)) to land the restored params directly
    under that mesh's shardings — valid even when the saving run used a
    different mesh shape.  With neither, arrays restore unsharded
    (single-process layouts).  `template` (an abstract or concrete
    pytree) pins dtypes/shapes when the target structure is ambiguous.
    """
    import jax

    target = os.path.abspath(_step_dir(path, step))
    ckptr = _checkpointer()
    t0 = time.perf_counter()
    restored = (ckptr.restore(target, template)
                if template is not None else ckptr.restore(target))
    _record_ckpt("restore", time.perf_counter() - t0,
                 _tree_bytes(restored), target, step, trainer)
    if mesh is None or axes is None:
        return restored
    from jax.sharding import NamedSharding

    from ray_tpu.parallel.sharding import (DEFAULT_RULES,
                                           logical_to_mesh_axes)

    rules = rules or DEFAULT_RULES

    def place(ax, x):
        spec = logical_to_mesh_axes(tuple(ax), rules)
        return jax.device_put(x, NamedSharding(mesh, spec))

    # axes leads the map: its leaves are axis-name tuples, and the
    # matching restored subtree (an array) is passed through whole
    return jax.tree.map(place, axes, restored,
                        is_leaf=lambda n: isinstance(n, tuple))


def latest_step(path: str) -> Optional[int]:
    """Largest step_N subdirectory under `path`, or None."""
    try:
        steps = [int(d[len("step_"):]) for d in os.listdir(path)
                 if d.startswith("step_") and
                 d[len("step_"):].isdigit()]
    except OSError:
        return None
    return max(steps) if steps else None


def sharded_checkpoint_to_air(path: str, step: Optional[int] = None):
    """Wrap a sharded checkpoint directory as an AIR Checkpoint so it
    flows through session.report / Tune bookkeeping like any other
    artifact (the directory itself stays in place — sharded checkpoints
    are too big to ship through the object store)."""
    from ray_tpu.air import Checkpoint

    return Checkpoint.from_dict({
        "sharded_checkpoint_path": os.path.abspath(
            _step_dir(path, step))})
