"""Distributed training orchestration (reference analog: python/ray/train).

Stack (reference call path 3.4 in SURVEY.md): Trainer.fit →
training_loop → BackendExecutor → WorkerGroup of actors → Backend
process-group setup → user train_loop_per_worker with air.session.
"""

from ray_tpu.air import session  # re-export: ray_tpu.train.session.report
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.backend_executor import (BackendExecutor,
                                            TrainingWorkerError)
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.gbdt_trainer import (GBDTModel, GBDTTrainer,
                                        LightGBMTrainer, XGBoostTrainer)
from ray_tpu.train.jax_backend import JaxConfig
from ray_tpu.train.huggingface import HuggingFaceTrainer
from ray_tpu.train.jax_trainer import JaxTrainer, jax_utils
from ray_tpu.train.torch_backend import (TorchConfig, TorchTrainer,
                                         prepare_data_loader,
                                         prepare_model)

from ray_tpu.train.grad_accum import accumulated_train_step
from ray_tpu.train.checkpointing import (latest_step, restore_sharded,
                                         save_sharded,
                                         sharded_checkpoint_to_air)
from ray_tpu.train.goodput import (GoodputTracker, HealthWatchdog,
                                   get_goodput_tracker,
                                   get_health_watchdog,
                                   get_train_recorder, watch_data,
                                   worker_skew)
from ray_tpu.train.telemetry import train_stats

__all__ = [
    "accumulated_train_step",
    "GoodputTracker", "HealthWatchdog", "get_goodput_tracker",
    "get_health_watchdog", "get_train_recorder", "watch_data",
    "worker_skew", "train_stats",
    "save_sharded", "restore_sharded", "latest_step",
    "sharded_checkpoint_to_air",
    "session", "Checkpoint", "ScalingConfig", "RunConfig", "FailureConfig",
    "CheckpointConfig", "Result", "Backend", "BackendConfig",
    "BackendExecutor", "TrainingWorkerError", "BaseTrainer",
    "DataParallelTrainer", "JaxConfig", "JaxTrainer", "jax_utils",
    "TorchConfig", "TorchTrainer", "prepare_model", "prepare_data_loader",
    "GBDTTrainer", "GBDTModel", "XGBoostTrainer", "LightGBMTrainer",
    "HuggingFaceTrainer",
]
