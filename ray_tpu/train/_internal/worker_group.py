"""WorkerGroup: a gang of train-worker actors.

Reference analog: train/_internal/worker_group.py:92 WorkerGroup / :17
RayTrainWorker.  Each worker is a ray_tpu actor pinned to its resource
bundle; the group runs arbitrary functions on all members in parallel
(`execute`), which is how the Backend plugins do their per-worker setup.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._internal.session import _TrainSession, TrainingResult


class RayTrainWorker:
    """Actor hosting one training process (one rank)."""

    def __init__(self):
        self._session: Optional[_TrainSession] = None

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process."""
        return fn(*args, **kwargs)

    def init_session(self, *, world_rank: int, local_rank: int,
                     world_size: int, trial_name: str, trial_id: str,
                     config: Dict[str, Any],
                     dataset_shards: Dict[str, Any],
                     checkpoint) -> None:
        self._session = _TrainSession(
            world_rank=world_rank, local_rank=local_rank,
            world_size=world_size, trial_name=trial_name,
            trial_id=trial_id, config=config,
            dataset_shards=dataset_shards, checkpoint=checkpoint)

    def start_training(self, train_fn: Callable) -> None:
        assert self._session is not None, "init_session first"
        sess = self._session
        cfg = sess.config
        if _fn_wants_config(train_fn):
            self._session.start(lambda: train_fn(cfg))
        else:
            self._session.start(train_fn)

    def next_result(self) -> TrainingResult:
        assert self._session is not None
        return self._session.next_result()

    def shutdown(self) -> bool:
        return True


def _fn_wants_config(fn: Callable) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    required = [p for p in sig.parameters.values()
                if p.default is p.empty and p.kind in
                (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(required) >= 1


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 placement_group=None):
        self.num_workers = num_workers
        self.resources_per_worker = dict(resources_per_worker
                                         or {"CPU": 1.0})
        self.placement_group = placement_group
        res = dict(self.resources_per_worker)
        opts: Dict[str, Any] = {
            "num_cpus": res.pop("CPU", 1.0),
        }
        if "TPU" in res:
            opts["num_tpus"] = res.pop("TPU")
        if res:
            opts["resources"] = res
        if placement_group is not None:
            opts["placement_group"] = placement_group
        self.workers = []
        for i in range(num_workers):
            o = dict(opts)
            if placement_group is not None:
                o["placement_group_bundle_index"] = i
            self.workers.append(
                ray_tpu.remote(**o)(RayTrainWorker).remote())

    def execute_async(self, fn: Callable, *args, **kwargs) -> List:
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs),
                           timeout=300)

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            [self.workers[rank].execute.remote(fn, *args, **kwargs)],
            timeout=300)[0]

    def shutdown(self):
        try:
            ray_tpu.get([w.shutdown.remote() for w in self.workers],
                        timeout=30)
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []

    def __len__(self):
        return len(self.workers)
