"""Worker-side train session: runs the user loop in a thread and hands
results to the driver one report at a time.

Reference analog: train/_internal/session.py:58 _TrainSession (:295
report) — same rendezvous semantics (report blocks until the driver
consumes the result) so workers and driver advance in lockstep.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session as air_session
from ray_tpu.air.checkpoint import Checkpoint


class TrainingResult:
    __slots__ = ("type", "metrics", "checkpoint", "error")

    def __init__(self, type: str, metrics=None, checkpoint=None, error=None):
        self.type = type            # "report" | "done" | "error"
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.error = error


class _TrainSession:
    def __init__(self, *, world_rank: int, local_rank: int, world_size: int,
                 trial_name: str = "", trial_id: str = "",
                 config: Optional[Dict[str, Any]] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 checkpoint: Optional[Checkpoint] = None):
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.world_size = world_size
        self.trial_name = trial_name
        self.trial_id = trial_id
        self.config = config or {}
        self.dataset_shards = dataset_shards or {}
        self.loaded_checkpoint = checkpoint
        self._result_q: "queue.Queue[TrainingResult]" = queue.Queue(maxsize=1)
        self._continue = threading.Semaphore(0)
        self._thread: Optional[threading.Thread] = None
        self.finished = False

    # -- called from the user loop thread ---------------------------------
    def report(self, metrics: Dict[str, Any], *, checkpoint=None) -> None:
        if checkpoint is not None and not isinstance(checkpoint, Checkpoint):
            checkpoint = Checkpoint.from_dict(checkpoint)
        self._result_q.put(TrainingResult("report", metrics=dict(metrics),
                                          checkpoint=checkpoint))
        self._continue.acquire()  # block until driver consumed it

    def get_dataset_shard(self, name: str):
        if name not in self.dataset_shards:
            raise KeyError(
                f"no dataset {name!r} registered with the trainer "
                f"(have {sorted(self.dataset_shards)})")
        return self.dataset_shards[name]

    # -- called from the actor (driver-facing) ----------------------------
    def start(self, train_fn: Callable[[], Any]) -> None:
        def runner():
            air_session._set_session(self)
            try:
                train_fn()
                self._result_q.put(TrainingResult("done"))
            except BaseException as e:  # noqa: BLE001 - forwarded to driver
                tb = traceback.format_exc()
                e._train_traceback = tb  # type: ignore[attr-defined]
                self._result_q.put(TrainingResult("error", error=e))
            finally:
                air_session._set_session(None)

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="train_loop")
        self._thread.start()

    def next_result(self, timeout: Optional[float] = None) -> TrainingResult:
        res = self._result_q.get(timeout=timeout)
        if res.type == "report":
            self._continue.release()
        else:
            self.finished = True
        return res
