"""Gradient-boosted decision trees on CPU actor gangs.

Reference analog: ``python/ray/train/gbdt_trainer.py:70 GBDTTrainer``
(+ the xgboost_ray/lightgbm_ray backends it drives).  Two layers here:

- :class:`GBDTTrainer` — a NATIVE distributed histogram-GBDT: training
  data shards across worker actors, each worker computes per-node
  gradient/hessian histograms for its shard, the driver aggregates
  histograms and picks splits (the classic distributed approximate
  algorithm xgboost's ``tree_method=hist`` uses), then broadcasts the
  split decisions.  Pure numpy on CPU actors — this is deliberately a
  TPU-free path, like the reference's (GBDTs don't map to the MXU).
- :class:`XGBoostTrainer` / :class:`LightGBMTrainer` — thin wrappers
  that drive the external libraries when they are installed
  (import-gated: this image ships neither, the native trainer is the
  tested path).

AIR integration: ``fit()`` routes through the Tuner like every trainer
(base_trainer.py), per-round metrics flow through ``session.report``,
and the fitted model rides an AIR ``Checkpoint``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.result import Result
from ray_tpu.train.base_trainer import BaseTrainer


# ---------------------------------------------------------------------------
# model: a list of flat trees
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Tree:
    feature: np.ndarray     # (n_nodes,) int, -1 = leaf
    threshold: np.ndarray   # (n_nodes,) float (bin upper edge)
    children: np.ndarray    # (n_nodes, 2) int
    value: np.ndarray       # (n_nodes,) float leaf weight

    def predict(self, X: np.ndarray) -> np.ndarray:
        node = np.zeros(len(X), np.int64)
        # depth-bounded trees: iterate until every row sits on a leaf
        for _ in range(64):
            feat = self.feature[node]
            active = feat >= 0
            if not active.any():
                break
            f = feat[active]
            go_right = (X[active, f] > self.threshold[node[active]])
            node[active] = self.children[node[active],
                                         go_right.astype(np.int64)]
        return self.value[node]


class GBDTModel:
    """Fitted ensemble; picklable, Checkpoint-serializable."""

    def __init__(self, trees: List[_Tree], base_score: float,
                 objective: str, learning_rate: float):
        self.trees = trees
        self.base_score = base_score
        self.objective = objective
        self.learning_rate = learning_rate

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        out = np.full(len(X), self.base_score, np.float64)
        for t in self.trees:
            out += self.learning_rate * t.predict(X)
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        m = self.predict_margin(np.asarray(X, np.float64))
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-m))
        return m

    def to_checkpoint(self) -> Checkpoint:
        return Checkpoint.from_dict({"gbdt_model": self})

    @staticmethod
    def from_checkpoint(ckpt: Checkpoint) -> "GBDTModel":
        return ckpt.to_dict()["gbdt_model"]


# ---------------------------------------------------------------------------
# worker actor: holds a shard, serves histogram passes
# ---------------------------------------------------------------------------

class _GBDTWorker:
    """One data shard + its running margin; every boosting operation is
    one batched numpy pass over the shard."""

    def __init__(self, X, y, bin_edges, objective: str,
                 base_score: float):
        self.X = np.asarray(X, np.float64)
        self.y = np.asarray(y, np.float64)
        self.objective = objective
        self.margin = np.full(len(self.y), base_score, np.float64)
        self.edges = [np.asarray(e) for e in bin_edges]
        # pre-binned features: (n_rows, n_feat) small ints
        self.binned = np.stack(
            [np.searchsorted(self.edges[j], self.X[:, j], side="left")
             for j in range(self.X.shape[1])], axis=1)
        self.node = np.zeros(len(self.y), np.int64)

    # -- gradients ---------------------------------------------------------
    def _grad_hess(self) -> Tuple[np.ndarray, np.ndarray]:
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-self.margin))
            return p - self.y, np.maximum(p * (1 - p), 1e-9)
        return self.margin - self.y, np.ones_like(self.y)  # squared error

    def start_round(self) -> None:
        self.g, self.h = self._grad_hess()
        self.node[:] = 0

    def node_histograms(self, nodes: List[int], n_bins: int):
        """Per requested node: (n_feat, n_bins) grad and hess sums —
        ONE vectorized bincount pass per feature over the whole shard."""
        out = {}
        n_feat = self.binned.shape[1]
        for nid in nodes:
            mask = self.node == nid
            if not mask.any():
                out[nid] = (np.zeros((n_feat, n_bins)),
                            np.zeros((n_feat, n_bins)))
                continue
            b = self.binned[mask]
            g = self.g[mask]
            h = self.h[mask]
            gh = np.empty((n_feat, n_bins))
            hh = np.empty((n_feat, n_bins))
            for j in range(n_feat):
                gh[j] = np.bincount(b[:, j], weights=g, minlength=n_bins)
                hh[j] = np.bincount(b[:, j], weights=h, minlength=n_bins)
            out[nid] = (gh, hh)
        return out

    def apply_splits(self, splits: Dict[int, Tuple[int, int, int, int]]):
        """splits: node -> (feature, bin_thresh, left_id, right_id);
        rows in split nodes move to their child."""
        for nid, (feat, bin_t, left, right) in splits.items():
            mask = self.node == nid
            go_right = self.binned[mask, feat] > bin_t
            ids = np.where(go_right, right, left)
            self.node[mask] = ids

    def finish_round(self, tree: _Tree, lr: float) -> Dict[str, float]:
        """Fold the new tree into the running margin; report shard loss
        stats for the driver to aggregate."""
        self.margin += lr * tree.predict(self.X)
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-self.margin))
            p = np.clip(p, 1e-12, 1 - 1e-12)
            loss = -np.mean(self.y * np.log(p)
                            + (1 - self.y) * np.log(1 - p))
            err = float(np.mean((p > 0.5) != (self.y > 0.5)))
        else:
            loss = float(np.mean((self.margin - self.y) ** 2))
            err = loss
        return {"loss_sum": float(loss) * len(self.y),
                "err_sum": err * len(self.y), "rows": len(self.y)}

    def label_stats(self):
        return float(self.y.sum()), len(self.y)

    def feature_quantiles(self, qs: np.ndarray):
        return [np.quantile(self.X[:, j], qs)
                for j in range(self.X.shape[1])]


class GBDTTrainer(BaseTrainer):
    """Distributed histogram gradient boosting (reference:
    train/gbdt_trainer.py:70; algorithmically the distributed hist
    scheme of xgboost-on-ray).

    ``datasets={"train": (X, y)}`` with numpy arrays, or a
    ray_tpu.data.Dataset whose columns are features plus
    ``label_column``.
    """

    def __init__(self, *, params: Optional[Dict[str, Any]] = None,
                 label_column: str = "label",
                 num_boost_round: int = 20,
                 num_workers: int = 2, n_bins: int = 32,
                 scaling_config=None, run_config=None, datasets=None,
                 resume_from_checkpoint=None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        p = dict(params or {})
        self.objective = p.get("objective", "reg:squarederror")
        self.max_depth = int(p.get("max_depth", 4))
        self.learning_rate = float(p.get("eta", p.get("learning_rate",
                                                      0.3)))
        self.reg_lambda = float(p.get("lambda", 1.0))
        self.min_child_weight = float(p.get("min_child_weight", 1e-3))
        self.label_column = label_column
        self.num_boost_round = num_boost_round
        self.num_workers = num_workers
        self.n_bins = n_bins

    # -- data plumbing -----------------------------------------------------
    def _shards(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        train = self.datasets.get("train")
        if train is None:
            raise ValueError('datasets={"train": ...} is required')
        if isinstance(train, tuple):
            X, y = np.asarray(train[0], np.float64), np.asarray(
                train[1], np.float64)
        else:  # ray_tpu.data.Dataset of feature columns + label
            rows = train.take_all()
            y = np.asarray([r[self.label_column] for r in rows],
                           np.float64)
            feat_keys = [k for k in rows[0] if k != self.label_column]
            X = np.asarray([[r[k] for k in feat_keys] for r in rows],
                           np.float64)
        n = self.num_workers
        idx = np.array_split(np.arange(len(y)), n)
        return [(X[i], y[i]) for i in idx]

    # -- driver-side split selection --------------------------------------
    def _best_splits(self, hists, parent_stats, next_id):
        """Given aggregated (grad, hess) histograms per node, choose the
        gain-maximizing (feature, bin) split per node (xgboost's exact
        gain formula with lambda regularization)."""
        splits, leaves = {}, {}
        lam = self.reg_lambda
        for nid, (gh, hh) in hists.items():
            G, H = parent_stats[nid]
            gl = np.cumsum(gh, axis=1)
            hl = np.cumsum(hh, axis=1)
            gr = G - gl
            hr = H - hl
            valid = (hl >= self.min_child_weight) & \
                    (hr >= self.min_child_weight)
            gain = 0.5 * (gl ** 2 / (hl + lam) + gr ** 2 / (hr + lam)
                          - G ** 2 / (H + lam))
            gain = np.where(valid, gain, -np.inf)
            j, b = np.unravel_index(int(np.argmax(gain)), gain.shape)
            if not np.isfinite(gain[j, b]) or gain[j, b] <= 1e-12:
                leaves[nid] = -G / (H + lam)
                continue
            left, right = next_id[0], next_id[0] + 1
            next_id[0] += 2
            splits[nid] = (int(j), int(b), left, right,
                           (float(gl[j, b]), float(hl[j, b])),
                           (float(gr[j, b]), float(hr[j, b])))
        return splits, leaves

    # -- the training loop (runs inside the tune trial) --------------------
    def training_loop(self) -> Result:
        import ray_tpu
        from ray_tpu.air import session

        shards = self._shards()
        # fractional so a gang + its tune-trial actor fit small CI boxes
        Worker = ray_tpu.remote(num_cpus=0.5)(_GBDTWorker)

        # global quantile bin edges (the role of xgboost's quantile
        # sketch).  Computed over the full feature matrix so the fitted
        # model is EXACTLY invariant to how rows shard across workers —
        # the distributed-hist correctness property the test pins.
        qs = np.linspace(0, 1, self.n_bins)[1:]
        X_all = np.concatenate([np.asarray(X, np.float64)
                                for X, _ in shards])
        q = np.quantile(X_all, qs, axis=0)  # (n_bins-1, n_feat)
        edges = [q[:, j] for j in range(X_all.shape[1])]
        del X_all

        ysum = sum(float(np.sum(y)) for _, y in shards)
        rows = sum(len(y) for _, y in shards)
        if self.objective == "binary:logistic":
            p0 = min(max(ysum / rows, 1e-6), 1 - 1e-6)
            base = float(np.log(p0 / (1 - p0)))
        else:
            base = ysum / rows

        workers = [Worker.remote(X, y, edges, self.objective, base)
                   for X, y in shards]
        n_bins = self.n_bins + 1  # searchsorted can land past last edge

        trees: List[_Tree] = []
        metrics: Dict[str, float] = {}
        for rnd in range(self.num_boost_round):
            ray_tpu.get([w.start_round.remote() for w in workers],
                        timeout=600)
            # grow one tree level-by-level
            feature = [-1]
            threshold = [0.0]
            children = [[-1, -1]]
            value = [0.0]
            next_id = [1]
            frontier = [0]
            parent_stats: Dict[int, Tuple[float, float]] = {}
            for depth in range(self.max_depth):
                if not frontier:
                    break
                parts = ray_tpu.get(
                    [w.node_histograms.remote(frontier, n_bins)
                     for w in workers], timeout=600)
                hists = {}
                for nid in frontier:
                    gh = sum(p[nid][0] for p in parts)
                    hh = sum(p[nid][1] for p in parts)
                    hists[nid] = (gh, hh)
                    if nid not in parent_stats:  # root: every feature's
                        # bins sum to the node's total (G, H)
                        parent_stats[nid] = (float(gh[0].sum()),
                                             float(hh[0].sum()))
                splits, leaves = self._best_splits(hists, parent_stats,
                                                   next_id)
                for nid, w_leaf in leaves.items():
                    value[nid] = float(w_leaf)
                apply_payload = {}
                for nid, (j, b, left, right, ls, rs) in splits.items():
                    while len(feature) < right + 1:
                        feature.append(-1)
                        threshold.append(0.0)
                        children.append([-1, -1])
                        value.append(0.0)
                    feature[nid] = j
                    threshold[nid] = float(edges[j][min(
                        b, len(edges[j]) - 1)])
                    children[nid] = [left, right]
                    parent_stats[left] = ls
                    parent_stats[right] = rs
                    apply_payload[nid] = (j, b, left, right)
                if apply_payload:
                    ray_tpu.get(
                        [w.apply_splits.remote(apply_payload)
                         for w in workers], timeout=600)
                frontier = [nid for s in splits.values()
                            for nid in (s[2], s[3])]
            # any still-unsplit frontier nodes become leaves
            lam = self.reg_lambda
            for nid in frontier:
                G, H = parent_stats[nid]
                value[nid] = float(-G / (H + lam))
            tree = _Tree(np.asarray(feature), np.asarray(threshold),
                         np.asarray(children), np.asarray(value))
            trees.append(tree)
            stats = ray_tpu.get(
                [w.finish_round.remote(tree, self.learning_rate)
                 for w in workers], timeout=600)
            rows = sum(s["rows"] for s in stats)
            metrics = {
                "train-loss": sum(s["loss_sum"] for s in stats) / rows,
                "train-error": sum(s["err_sum"] for s in stats) / rows,
                "training_iteration": rnd + 1,
            }
            model = GBDTModel(trees, base, self.objective,
                              self.learning_rate)
            session.report(metrics, checkpoint=model.to_checkpoint())
        for w in workers:
            ray_tpu.kill(w)
        return Result(metrics=metrics,
                      checkpoint=GBDTModel(
                          trees, base, self.objective,
                          self.learning_rate).to_checkpoint())


class XGBoostTrainer(GBDTTrainer):
    """Reference-parity name (train/xgboost/xgboost_trainer.py).  Uses
    the real xgboost library when installed; otherwise falls back to
    the native distributed GBDT above (same params dialect for the
    common keys: objective, max_depth, eta, lambda)."""

    def training_loop(self) -> Result:
        try:
            import xgboost  # noqa: F401
        except ImportError:
            return super().training_loop()
        return self._xgb_loop()

    def _xgb_loop(self) -> Result:
        import xgboost as xgb
        from ray_tpu.air import session

        shards = self._shards()
        X = np.concatenate([s[0] for s in shards])
        y = np.concatenate([s[1] for s in shards])
        dtrain = xgb.DMatrix(X, label=y)
        params = {"objective": self.objective,
                  "max_depth": self.max_depth,
                  "eta": self.learning_rate,
                  "lambda": self.reg_lambda}
        evals_result: Dict[str, Any] = {}
        booster = xgb.train(params, dtrain,
                            num_boost_round=self.num_boost_round,
                            evals=[(dtrain, "train")],
                            evals_result=evals_result, verbose_eval=False)
        metric_name, series = next(iter(evals_result["train"].items()))
        metrics = {f"train-{metric_name}": series[-1],
                   "training_iteration": self.num_boost_round}
        ckpt = Checkpoint.from_dict({"xgb_model": booster.save_raw()})
        session.report(metrics, checkpoint=ckpt)
        return Result(metrics=metrics, checkpoint=ckpt)


class LightGBMTrainer(XGBoostTrainer):
    """Reference-parity name (train/lightgbm/lightgbm_trainer.py);
    delegates to the native GBDT when lightgbm is absent."""

    def training_loop(self) -> Result:
        try:
            import lightgbm  # noqa: F401
        except ImportError:
            return GBDTTrainer.training_loop(self)
        import lightgbm as lgb
        from ray_tpu.air import session

        shards = self._shards()
        X = np.concatenate([s[0] for s in shards])
        y = np.concatenate([s[1] for s in shards])
        obj = ("binary" if self.objective == "binary:logistic"
               else "regression")
        model = lgb.train(
            {"objective": obj, "max_depth": self.max_depth,
             "learning_rate": self.learning_rate},
            lgb.Dataset(X, label=y),
            num_boost_round=self.num_boost_round)
        metrics = {"training_iteration": self.num_boost_round}
        ckpt = Checkpoint.from_dict(
            {"lgbm_model": model.model_to_string()})
        session.report(metrics, checkpoint=ckpt)
        return Result(metrics=metrics, checkpoint=ckpt)
