"""Torch backend + TorchTrainer: CPU/gloo data-parallel training.

Role-equivalent of the reference's Torch Train backend (reference
``train/torch/config.py:29 TorchConfig``, ``:70
_setup_torch_process_group`` = ``dist.init_process_group``; loop utils
``train/torch/train_loop_utils.py:28 prepare_model`` wrapping DDP).
The TPU build's flagship is JaxTrainer — this backend exists for
ecosystem parity (the image ships CPU torch, so gloo only; a CUDA
deployment would pass backend="nccl").
"""

from __future__ import annotations

import dataclasses
import socket
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


@dataclasses.dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"            # "nccl" on CUDA deployments
    init_timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return TorchBackend


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _get_node_ip() -> str:
    return socket.gethostbyname(socket.gethostname())


def _setup_torch_process_group(backend: str, init_method: str,
                               rank: int, world_size: int,
                               timeout_s: float, local_rank: int = 0,
                               local_world_size: int = 1):
    """Reference: train/torch/config.py:70 _setup_torch_process_group."""
    import datetime
    import os

    import torch.distributed as dist

    # torchrun-style env vars: accelerate/transformers detect
    # distributed mode through LOCAL_RANK/WORLD_SIZE (env-gated, NOT
    # by probing the process group), so without these a
    # HuggingFaceTrainer gang would silently train unsynchronized
    # single-process copies.  LOCAL_RANK is the rank WITHIN the node
    # (device placement / local-process-zero gating on multi-node
    # gangs), computed by the backend from worker node placement.
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    os.environ["LOCAL_RANK"] = str(local_rank)
    os.environ["LOCAL_WORLD_SIZE"] = str(local_world_size)
    host_port = init_method.removeprefix("tcp://")
    if ":" in host_port:
        host, _, port = host_port.rpartition(":")
        os.environ.setdefault("MASTER_ADDR", host)
        os.environ.setdefault("MASTER_PORT", port)
    if dist.is_initialized():
        return
    dist.init_process_group(
        backend=backend, init_method=init_method, rank=rank,
        world_size=world_size,
        timeout=datetime.timedelta(seconds=timeout_s))


def _teardown_torch_process_group():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()


class TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig) -> None:
        n = len(worker_group)
        if n <= 1:
            return
        import ray_tpu

        ip = worker_group.execute_single(0, _get_node_ip)
        port = worker_group.execute_single(0, _pick_port)
        init_method = f"tcp://{ip}:{port}"
        # node-local ranks: group workers by their node ip
        ips = ray_tpu.get([w.execute.remote(_get_node_ip)
                           for w in worker_group.workers],
                          timeout=backend_config.init_timeout_s)
        seen: Dict[str, int] = {}
        local_ranks = []
        for wip in ips:
            local_ranks.append(seen.get(wip, 0))
            seen[wip] = seen.get(wip, 0) + 1
        ray_tpu.get([w.execute.remote(
            _setup_torch_process_group, backend_config.backend,
            init_method, i, n, backend_config.init_timeout_s,
            local_ranks[i], seen[ips[i]])
            for i, w in enumerate(worker_group.workers)],
            timeout=backend_config.init_timeout_s + 30)

    def on_shutdown(self, worker_group, backend_config) -> None:
        try:
            worker_group.execute(_teardown_torch_process_group)
        except Exception:  # noqa: BLE001 - workers may be dead
            pass


class TorchTrainer(DataParallelTrainer):
    """DataParallelTrainer + TorchConfig (reference: TorchTrainer)."""

    _default_backend_config = TorchConfig()

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=torch_config or TorchConfig(),
            scaling_config=scaling_config, run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


# -- worker-side loop utils (reference: train_loop_utils.py) ---------------

def prepare_model(model):
    """Wrap in DistributedDataParallel when a process group is active
    (reference: train/torch/train_loop_utils.py:28 prepare_model)."""
    import torch.distributed as dist

    if dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across workers with a DistributedSampler
    (reference: train_loop_utils.py prepare_data_loader)."""
    import torch.distributed as dist

    if not (dist.is_initialized() and dist.get_world_size() > 1):
        return loader
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    return DataLoader(loader.dataset, batch_size=loader.batch_size,
                      sampler=DistributedSampler(loader.dataset),
                      num_workers=0, collate_fn=loader.collate_fn,
                      drop_last=loader.drop_last)
