"""Step-level telemetry for training loops (the Podracer discipline:
TPU-utilization work is driven by step-time histograms, nothing else).

``instrument_train_step`` wraps a jitted train step with host-side
timing — a ``perf_counter`` pair around the call, no device syncs are
added, so under async dispatch the recorded time is dispatch time until
the pipeline backpressures and device-step time after (exactly what a
throughput investigation needs).  Each distinct abstract signature of
the batch argument (leaf shapes/dtypes) counts one compile event: a
recompile storm shows up as a climbing ``train_compile_events_total``
long before anyone reads XLA logs.

Metrics land in ``util/metrics.py`` (published to the dashboard
``/metrics`` page through the GCS-KV snapshot path) and in an
in-process ``stats()`` snapshot mirroring the serve engine's
``engine_stats()`` shape.
"""

from __future__ import annotations

import collections
import functools
import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu._private import telemetry as _core

_STEP_BOUNDS_MS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0, 2500.0, 5000.0, 10000.0, 30000.0)

_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None


def _train_metrics() -> Dict[str, Any]:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            tags = ("trainer",)
            _metrics = {
                "step_time": Histogram(
                    "train_step_time_ms",
                    "host walltime per train step call",
                    boundaries=_STEP_BOUNDS_MS, tag_keys=tags),
                "examples_per_sec": Gauge(
                    "train_examples_per_sec",
                    "examples consumed per second (last step)",
                    tag_keys=tags),
                "steps": Counter(
                    "train_steps_total", "train step calls",
                    tag_keys=tags),
                "compiles": Counter(
                    "train_compile_events_total",
                    "distinct batch signatures seen (one XLA compile "
                    "each)", tag_keys=tags),
            }
        return _metrics


class TrainTelemetry:
    """Per-trainer recorder; cheap enough to call once per step."""

    def __init__(self, name: str = "default", history: int = 4096):
        self.name = name
        self._m = _train_metrics()
        self._tags = {"trainer": name}
        self._lock = threading.Lock()
        self._durs: collections.deque = collections.deque(maxlen=history)
        self._steps = 0
        self._compiles = 0
        self._examples = 0
        self._last_eps = 0.0

    def record_step(self, dur_s: float,
                    examples: Optional[int] = None) -> None:
        with self._lock:
            self._durs.append(float(dur_s))
            self._steps += 1
            if examples:
                self._examples += int(examples)
                if dur_s > 0:
                    self._last_eps = examples / dur_s
        self._m["step_time"].observe(dur_s * 1e3, tags=self._tags)
        self._m["steps"].inc(tags=self._tags)
        if examples and dur_s > 0:
            self._m["examples_per_sec"].set(
                round(examples / dur_s, 1), tags=self._tags)

    def record_compile(self, signature: str = "") -> None:
        with self._lock:
            self._compiles += 1
        self._m["compiles"].inc(tags=self._tags)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            durs = list(self._durs)
            out = {"trainer": self.name, "steps": self._steps,
                   "compiles": self._compiles,
                   "examples": self._examples,
                   "examples_per_sec": round(self._last_eps, 1)}
        out["step_time_ms"] = _core.summarize([d * 1e3 for d in durs])
        return out


_telemetries: Dict[str, TrainTelemetry] = {}
_telemetries_lock = threading.Lock()


def get_train_telemetry(name: str = "default") -> TrainTelemetry:
    """Process-wide TrainTelemetry singleton per trainer name."""
    with _telemetries_lock:
        tel = _telemetries.get(name)
        if tel is None:
            tel = _telemetries[name] = TrainTelemetry(name)
        return tel


def telemetry_names() -> list:
    """Trainer names with step telemetry in this process."""
    with _telemetries_lock:
        return sorted(_telemetries)


def train_stats(name: str = "default") -> Dict[str, Any]:
    """Snapshot for the named trainer (empty-shaped if never stepped).

    Beyond the step-time block this carries the trainwatch view
    (train/goodput.py): ``anatomy`` (per-step wall decomposed into
    data_wait/h2d/dispatch/device_compute/compile/checkpoint, legs
    summing exactly to the wall), ``goodput`` (rolling productive
    device time over loop wall), ``health`` (watchdog EWMA state and
    anomaly dumps), ``checkpoint`` (save/restore counters), and
    ``flightrec`` (the trainer's journal occupancy)."""
    from ray_tpu.train.goodput import trainwatch_blocks

    out = get_train_telemetry(name).stats()
    out.update(trainwatch_blocks(name))
    return out


def _batch_signature(batch: Any) -> tuple:
    """Abstract signature of the batch pytree: leaf shapes + dtypes.
    A fresh signature means the jitted step compiles a new program."""
    import jax

    return tuple(
        (tuple(getattr(x, "shape", ())),
         str(getattr(x, "dtype", type(x).__name__)))
        for x in jax.tree_util.tree_leaves(batch))


def _leading_dim(batch: Any) -> Optional[int]:
    import jax

    for leaf in jax.tree_util.tree_leaves(batch):
        shape = getattr(leaf, "shape", None)
        if shape:
            return int(shape[0])
    return None


def instrument_train_step(step_fn: Callable,
                          telemetry: Optional[TrainTelemetry] = None,
                          batch_arg: int = 2) -> Callable:
    """Wrap a (jitted) train step with step-time / compile / throughput
    telemetry.  ``batch_arg`` is the positional index of the batch
    pytree (2 for the canonical ``step(params, opt_state, batch)``);
    out-of-range indices simply skip the examples/sec gauge."""
    tel = telemetry or get_train_telemetry()
    seen: set = set()

    @functools.wraps(step_fn)
    def wrapped(*args, **kwargs):
        batch = args[batch_arg] if len(args) > batch_arg else None
        examples = None
        if batch is not None:
            try:
                sig = _batch_signature(batch)
                examples = _leading_dim(batch)
            except Exception:  # noqa: BLE001 - exotic batch types
                sig = None
            if sig is not None and sig not in seen:
                seen.add(sig)
                tel.record_compile(str(sig))
        t0 = time.perf_counter()
        out = step_fn(*args, **kwargs)
        tel.record_step(time.perf_counter() - t0, examples=examples)
        return out

    wrapped.__wrapped__ = step_fn
    wrapped.telemetry = tel
    return wrapped
