"""BaseTrainer (reference analog: train/base_trainer.py:38; its fit()
at :338 routes through a single-trial Tuner — ours does the same once
ray_tpu.tune is present, falling back to direct execution)."""

from __future__ import annotations

import abc
from typing import Any, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result


class BaseTrainer(abc.ABC):
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    @abc.abstractmethod
    def training_loop(self) -> Result:
        """Run the training; called inside the trial."""

    def fit(self) -> Result:
        """Run to completion as a one-trial tune experiment (reference
        base_trainer.py:338,353: fit() routes through a Tuner)."""
        from ray_tpu.tune.trainable_adapter import fit_via_tune

        return fit_via_tune(self)

    def as_trainable(self):
        """Wrap as a tune function-trainable (reference
        base_trainer.py:405 TrainTrainable)."""
        trainer = self

        def train_func(config):
            t = trainer
            if config:
                import copy

                t = copy.copy(trainer)
                t._apply_trial_config(config)
            result = t.training_loop()
            return result

        train_func.__name__ = type(self).__name__
        return train_func

    def _apply_trial_config(self, config: Dict[str, Any]) -> None:
        """Tune param overrides; subclasses merge into their loop config."""
