"""JaxTrainer — the flagship trainer (BASELINE.json north star: "Ray
Train's TorchTrainer/DataParallelTrainer gains a JaxTrainer whose
BackendConfig initializes jax.distributed and maps the NCCL allreduce to
XLA collectives over ICI").

DataParallelTrainer + JaxConfig, plus worker-side helpers that replace
the reference's ``prepare_model`` DDP/FSDP wrapping
(train/torch/train_loop_utils.py:28,72-114) with mesh/sharding setup:

    def loop(cfg):
        mesh = jax_utils.get_mesh()                # worker's device mesh
        params = jax_utils.shard_pytree(params, axes, mesh)
        step = jax_utils.build_train_step(loss_fn, tx, mesh, axes)
        ...
        session.report({"loss": l}, checkpoint=...)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax_backend import JaxConfig


class JaxTrainer(DataParallelTrainer):
    _default_backend_config = JaxConfig()

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config, run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)


class jax_utils:
    """Worker-side helpers (importable functions grouped for discovery)."""

    @staticmethod
    def get_mesh(spec=None):
        """Mesh over this worker's addressable devices (single-host) or
        the global mesh (jax.distributed mode)."""
        from ray_tpu.parallel import make_mesh

        return make_mesh(spec)

    @staticmethod
    def shard_pytree(tree, logical_axes, mesh, rules=None):
        from ray_tpu.parallel import sharding

        return sharding.shard_params(
            tree, logical_axes, mesh,
            rules=rules or sharding.DEFAULT_RULES)

    @staticmethod
    def build_train_step(loss_fn, tx, mesh=None, logical_axes=None,
                         rules=None, donate: bool = True,
                         telemetry: bool = True,
                         telemetry_name: str = "jax_trainer",
                         health: bool = False):
        """jitted (params, opt_state, batch) -> (params, opt_state, loss)
        with optional sharding constraints from logical_axes.

        telemetry=True (default) wraps the step with host-side
        step-time histograms, examples/sec gauges, compile-event
        counters (train/telemetry.py — perf_counter pairs only, no
        added device syncs) AND the trainwatch anatomy/goodput
        recorder (train/goodput.py); read them back via
        ``jax_utils.train_stats(telemetry_name)``.

        health=True makes the step additionally return cheap device
        scalars as a 4th output — ``{"loss", "grad_norm",
        "nonfinite"}``, all computed INSIDE the jitted program (no
        extra dispatch, no host transfer in the jaxpr) — and arms the
        host-side watchdog: EWMA z-score spikes and NaN/inf trip a
        ``train_anomaly`` journal event plus a flight-recorder
        postmortem naming the step, trainer, and batch signature.
        Reading the scalars fences each step (one small D2H), which
        is what buys one-step detection latency."""
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.parallel import sharding

        in_shardings = None
        if mesh is not None and logical_axes is not None:
            p_shard = sharding.param_shardings(
                logical_axes, mesh, rules or sharding.DEFAULT_RULES)
            in_shardings = (p_shard, None, None)

        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if not health:
                return new_params, opt_state, loss
            nonfinite = functools.reduce(
                jnp.add,
                [jnp.sum(~jnp.isfinite(g)).astype(jnp.int32)
                 for g in jax.tree_util.tree_leaves(grads)],
                jnp.int32(0))
            scalars = {"loss": loss,
                       "grad_norm": optax.global_norm(grads),
                       "nonfinite": nonfinite}
            return new_params, opt_state, loss, scalars

        kw: Dict[str, Any] = {}
        if in_shardings is not None:
            kw["in_shardings"] = in_shardings
        if donate:
            kw["donate_argnums"] = (0, 1)
        jitted = jax.jit(step, **kw)
        if not telemetry:
            return jitted
        from ray_tpu._private.device_stats import get_registry
        from ray_tpu.train.goodput import (get_goodput_tracker,
                                           get_health_watchdog,
                                           instrument_trainwatch)
        from ray_tpu.train.telemetry import (get_train_telemetry,
                                             instrument_train_step)

        # perf observatory first (compiled-cost harvest + recompile
        # watchdog under "train.step"), host step-time telemetry next,
        # trainwatch anatomy/health on the outside — all are
        # signature-keyed; only health mode adds a (deliberate) sync
        n_dev = int(mesh.size) if mesh is not None else 1
        jitted = get_registry().instrument("train.step", jitted,
                                           n_devices=n_dev)
        jitted = instrument_train_step(
            jitted, telemetry=get_train_telemetry(telemetry_name))
        wrapped = instrument_trainwatch(
            jitted,
            tracker=get_goodput_tracker(telemetry_name),
            watchdog=(get_health_watchdog(telemetry_name)
                      if health else None))
        wrapped._raw_step = step   # the jaxpr-guard hook (tests)
        return wrapped

    @staticmethod
    def train_stats(name: str = "jax_trainer"):
        """Step-time percentiles / compile counts recorded by
        ``build_train_step`` steps in THIS process (workers call it
        inside the loop and ``session.report`` it up)."""
        from ray_tpu.train.telemetry import train_stats

        return train_stats(name)

    @staticmethod
    def allreduce_gradients(grads, op: str = "mean",
                            group_name: str = "train"):
        from ray_tpu.train.jax_backend import allreduce_gradients

        return allreduce_gradients(grads, op=op, group_name=group_name)
