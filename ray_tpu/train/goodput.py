"""Trainwatch: training goodput anatomy, data-stall attribution, and
the loss/grad health watchdog.

The serve stack answers "where did this request's latency go?" with a
clamped critical-path decomposition (serve/telemetry.py
``critical_path``).  This module is the training-side mirror: every
train step's wall time decomposes into

    data_wait + h2d + dispatch + device_compute + compile + checkpoint

on the shared ``perf_counter`` clock, with each leg clamped into the
step window so the components sum EXACTLY to the measured wall — a
step that stalls on the input pipeline reads as ``data_wait``
dominance, a recompile storm as ``compile``, a checkpoint pause as
``checkpoint``, and only ``device_compute`` counts as *goodput* (the
Podracer discipline: productive device seconds over loop wall
seconds, compiles and stalls excluded).

Three cooperating pieces:

* ``GoodputTracker`` — per-trainer sample pools + the rolling goodput
  ratio.  Producers feed it through ``note_data_wait`` /
  ``note_h2d`` / ``record_checkpoint`` pending buckets that drain
  into the NEXT ``record_step`` window, so iterator stalls and
  checkpoint pauses land in the goodput denominator without the loop
  having to thread timestamps around.
* ``watch_data(iterable)`` — wraps the batch iterator; ``__next__``
  walltime becomes the ``data_wait`` leg, so input-bound vs
  compute-bound is a read-off from ``train_stats()["anatomy"]``.
* ``HealthWatchdog`` — host-side EWMA z-score spike + NaN/inf
  detector over the cheap device scalars ``build_train_step(...,
  health=True)`` returns (loss, global grad-norm, nonfinite-leaf
  count — all computed INSIDE the jitted step, no extra dispatch).
  Every observation journals a ``train_step`` event into a
  per-trainer flight recorder; an anomaly journals ``train_anomaly``
  and dumps a postmortem (``_private/flightrec.py`` dump path) naming
  the step index, batch signature, and the last-k metric trail.

Clock discipline: ``time.perf_counter()`` only, and every ``record_*``
/ ``observe`` takes an injectable ``now``/``ts`` for deterministic
tests — the graftcheck ``wallclock-in-telemetry`` rule covers this
file.

Env knobs: ``RAYTPU_TRAINWATCH=0`` disables anatomy/goodput/health
recording process-wide (the wrappers degrade to bare step calls);
the flight-recorder side honors ``RAYTPU_FLIGHTREC`` as usual.
"""

from __future__ import annotations

import collections
import functools
import math
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from ray_tpu._private import telemetry as _core
from ray_tpu._private.flightrec import FlightRecorder

__all__ = [
    "ANATOMY_COMPONENTS", "GoodputTracker", "HealthWatchdog",
    "DataWaitProbe", "watch_data", "get_goodput_tracker",
    "get_health_watchdog", "get_train_recorder", "instrument_trainwatch",
    "trainwatch_blocks", "registered_trainers", "dominant_component",
    "worker_skew",
]

#: the step-anatomy legs; together with ``step_wall_ms`` these are the
#: keys of every anatomy block, and per step the legs sum to
#: ``step_wall_ms`` exactly (modulo float rounding) by construction —
#: the same clamping contract as serve's ``critical_path()``.
ANATOMY_COMPONENTS = ("data_wait_ms", "h2d_ms", "dispatch_ms",
                      "device_compute_ms", "compile_ms", "checkpoint_ms")


def _enabled() -> bool:
    return os.environ.get("RAYTPU_TRAINWATCH", "1").lower() \
        not in ("0", "false", "off")


class GoodputTracker:
    """One trainer's step-anatomy sample pools and goodput window.

    The decomposition unit is one LOOP ITERATION: pending buckets
    (data wait from the iterator probe, h2d from an explicitly timed
    transfer, checkpoint pauses) accumulated since the last step drain
    into the next ``record_step`` call, whose wall is

        wall = pending_data_wait + pending_h2d + pending_checkpoint
               + step_call_duration

    and whose legs are clamped, in stall-first order, into that wall:
    each leg takes at most the remaining budget, and ``dispatch``
    absorbs the residual — so the legs sum to the wall exactly.  On a
    fresh-signature call the step call IS the XLA trace+compile, so
    the call duration lands in ``compile`` and goodput's numerator
    gets nothing (first-step time is compile time).  Otherwise the
    call duration is the ``device_compute`` leg — under async dispatch
    that is dispatch time until the pipeline backpressures and device
    time after, exactly the host-side timing contract
    train/telemetry.py documents (health mode fences per step, making
    the leg true device time).
    """

    def __init__(self, name: str = "default", history: int = 4096,
                 window: int = 256, enabled: Optional[bool] = None):
        self.name = name
        self.enabled = _enabled() if enabled is None else bool(enabled)
        self.window = int(window)
        self._lock = threading.Lock()
        self._samples: Dict[str, collections.deque] = {
            comp: collections.deque(maxlen=history)
            for comp in ANATOMY_COMPONENTS}
        self._wall: collections.deque = collections.deque(maxlen=history)
        #: per-step raw decompositions (ms) — the exact-sum invariant
        #: is asserted over these, not over pooled percentiles
        self._last_steps: collections.deque = collections.deque(maxlen=64)
        #: rolling (wall_s, productive_s) pairs for the goodput ratio
        self._window: collections.deque = collections.deque(
            maxlen=self.window)
        self._steps = 0
        self._pending_data_wait = 0.0
        self._pending_h2d = 0.0
        self._pending_ckpt = 0.0
        self._ckpt = {
            "saves": 0, "restores": 0,
            "save_ms": collections.deque(maxlen=history),
            "restore_ms": collections.deque(maxlen=history),
            "bytes_written": 0, "bytes_read": 0, "last_step": None,
        }

    # -- producers -----------------------------------------------------

    def note_data_wait(self, seconds: float) -> None:
        """Batch-iterator stall time since the last step (the
        ``watch_data`` probe calls this per ``__next__``)."""
        if not self.enabled:
            return
        with self._lock:
            self._pending_data_wait += max(0.0, float(seconds))

    def note_h2d(self, seconds: float) -> None:
        """An explicitly timed host→device transfer for the next step
        (e.g. a ``device_put`` of the batch the loop times itself)."""
        if not self.enabled:
            return
        with self._lock:
            self._pending_h2d += max(0.0, float(seconds))

    def record_checkpoint(self, kind: str, dur_s: float,
                          nbytes: int = 0,
                          step: Optional[int] = None) -> None:
        """One checkpoint ``save``/``restore`` pause of ``dur_s``
        seconds; lands in the next step's ``checkpoint`` leg and the
        goodput denominator, plus the ``checkpoint`` counter block."""
        if not self.enabled:
            return
        dur_s = max(0.0, float(dur_s))
        with self._lock:
            self._pending_ckpt += dur_s
            if kind == "save":
                self._ckpt["saves"] += 1
                self._ckpt["save_ms"].append(dur_s * 1e3)
                self._ckpt["bytes_written"] += int(nbytes)
            else:
                self._ckpt["restores"] += 1
                self._ckpt["restore_ms"].append(dur_s * 1e3)
                self._ckpt["bytes_read"] += int(nbytes)
            if step is not None:
                self._ckpt["last_step"] = int(step)

    def record_step(self, call_s: float, *, compiled: bool = False,
                    device_s: Optional[float] = None,
                    now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Close one loop-iteration window around a step call of
        ``call_s`` seconds, draining the pending stall buckets.

        ``compiled`` marks a fresh-signature call (the whole call is
        the ``compile`` leg); ``device_s`` optionally overrides the
        device-compute leg (e.g. from the observatory's ``train.step``
        invoke windows) — anything of the call it does not explain is
        ``dispatch``.  Returns the per-step decomposition dict (ms)
        whose legs sum exactly to ``wall_ms``."""
        if not self.enabled:
            return None
        del now  # accepted for signature symmetry with record_* peers
        call_s = max(0.0, float(call_s))
        with self._lock:
            data_wait = self._pending_data_wait
            h2d = self._pending_h2d
            ckpt = self._pending_ckpt
            self._pending_data_wait = 0.0
            self._pending_h2d = 0.0
            self._pending_ckpt = 0.0

            wall = data_wait + h2d + ckpt + call_s
            budget = wall

            def take(x: float) -> float:
                nonlocal budget
                v = min(max(0.0, x), budget)
                budget -= v
                return v

            # stall-first clamp order: stalls are measured directly,
            # compute legs divide whatever the call actually took
            data_wait = take(data_wait)
            ckpt = take(ckpt)
            h2d = take(h2d)
            if compiled:
                compile_ = take(call_s)
                device = take(0.0)
            else:
                compile_ = take(0.0)
                device = take(call_s if device_s is None else device_s)
            dispatch = budget  # residual — legs now sum to wall exactly
            ms = 1e3
            step_rec = {
                "step_wall_ms": wall * ms,
                "data_wait_ms": data_wait * ms,
                "h2d_ms": h2d * ms,
                "dispatch_ms": dispatch * ms,
                "device_compute_ms": device * ms,
                "compile_ms": compile_ * ms,
                "checkpoint_ms": ckpt * ms,
            }
            self._steps += 1
            self._wall.append(step_rec["step_wall_ms"])
            for comp in ANATOMY_COMPONENTS:
                self._samples[comp].append(step_rec[comp])
            self._last_steps.append(step_rec)
            self._window.append((wall, device))
            return step_rec

    # -- cold readers --------------------------------------------------

    def last_steps(self) -> List[Dict[str, Any]]:
        """The most recent raw per-step decompositions (ms)."""
        with self._lock:
            return [dict(s) for s in self._last_steps]

    def anatomy(self) -> Dict[str, Any]:
        """``train_stats()["anatomy"]``: pooled percentiles per leg
        plus the step wall itself, stable-shaped when never stepped."""
        with self._lock:
            pools = {comp: list(self._samples[comp])
                     for comp in ANATOMY_COMPONENTS}
            wall = list(self._wall)
        out: Dict[str, Any] = {"step_wall_ms": _core.summarize(wall)}
        for comp in ANATOMY_COMPONENTS:
            out[comp] = _core.summarize(pools[comp])
        return out

    def goodput_stats(self) -> Dict[str, Any]:
        """``train_stats()["goodput"]``: productive device seconds
        over loop wall seconds across the rolling window."""
        with self._lock:
            pairs = list(self._window)
            steps = self._steps
        wall = sum(w for w, _ in pairs)
        productive = sum(p for _, p in pairs)
        return {
            "ratio": (round(productive / wall, 4) if wall > 0 else None),
            "productive_s": round(productive, 6),
            "wall_s": round(wall, 6),
            "steps": steps,
            "window": self.window,
        }

    def checkpoint_stats(self) -> Dict[str, Any]:
        """``train_stats()["checkpoint"]`` counter block."""
        with self._lock:
            c = self._ckpt
            save_ms = list(c["save_ms"])
            restore_ms = list(c["restore_ms"])
            out = {"saves": c["saves"], "restores": c["restores"],
                   "bytes_written": c["bytes_written"],
                   "bytes_read": c["bytes_read"],
                   "last_step": c["last_step"]}
        out["save_ms"] = _core.summarize(save_ms)
        out["restore_ms"] = _core.summarize(restore_ms)
        return out


class DataWaitProbe:
    """Iterator wrapper timing ``__next__`` into a tracker's
    ``data_wait`` bucket — wrap the batch source once and input-bound
    steps become visible without touching the loop body."""

    def __init__(self, iterable: Iterable, tracker: GoodputTracker):
        self._it = iter(iterable)
        self.tracker = tracker

    def __iter__(self) -> "DataWaitProbe":
        return self

    def __next__(self) -> Any:
        t0 = time.perf_counter()
        try:
            item = next(self._it)
        finally:
            self.tracker.note_data_wait(time.perf_counter() - t0)
        return item


def watch_data(iterable: Iterable,
               tracker: Optional[GoodputTracker] = None,
               trainer: str = "default") -> DataWaitProbe:
    """Wrap a batch iterator so its stall time lands in the named
    trainer's ``data_wait`` leg."""
    return DataWaitProbe(iterable,
                         tracker or get_goodput_tracker(trainer))


# ---------------------------------------------------------------------------
# health watchdog
# ---------------------------------------------------------------------------

class _Ewma:
    """EWMA mean/variance over finite observations (NaN/inf are
    detected, never folded into the running statistics)."""

    def __init__(self, alpha: float = 0.1):
        self.alpha = alpha
        self.n = 0
        self.mean = 0.0
        self.var = 0.0
        self.last: Optional[float] = None

    def z(self, x: float) -> Optional[float]:
        if self.n < 1 or self.var <= 0:
            return None
        return (x - self.mean) / math.sqrt(self.var + 1e-12)

    def update(self, x: float) -> None:
        self.last = x
        if self.n == 0:
            self.mean = x
        else:
            d = x - self.mean
            self.mean += self.alpha * d
            self.var = (1 - self.alpha) * (self.var
                                           + self.alpha * d * d)
        self.n += 1

    def stats(self) -> Dict[str, Any]:
        return {"last": self.last,
                "ewma": round(self.mean, 6) if self.n else None,
                "ewma_std": (round(math.sqrt(max(0.0, self.var)), 6)
                             if self.n else None)}


class HealthWatchdog:
    """Host-side detector over the per-step health scalars.

    Triggers: non-finite loss, non-finite grad norm, any non-finite
    gradient leaf elements, and EWMA z-score spikes of loss or grad
    norm past ``z_threshold`` (after ``warmup`` finite observations).
    Every observation journals ``train_step``; an anomaly journals
    ``train_anomaly`` and dumps a flight-recorder postmortem naming
    the step, trainer, batch signature, and the last-k metric trail
    — at most one dump per ``dump_cooldown`` steps so a NaN'd run
    does not flood the dump dir."""

    def __init__(self, trainer: str = "default", *,
                 ewma_alpha: float = 0.1, z_threshold: float = 6.0,
                 warmup: int = 8, trail: int = 32,
                 dump_cooldown: int = 50,
                 recorder: Optional[FlightRecorder] = None):
        self.trainer = trainer
        self.z_threshold = float(z_threshold)
        self.warmup = int(warmup)
        self.dump_cooldown = int(dump_cooldown)
        self.recorder = recorder or get_train_recorder(trainer)
        self._lock = threading.Lock()
        self._loss = _Ewma(ewma_alpha)
        self._grad = _Ewma(ewma_alpha)
        self._trail: collections.deque = collections.deque(maxlen=trail)
        self.observed = 0
        self.anomalies = 0
        self.last_anomaly: Optional[Dict[str, Any]] = None
        self.dumps: List[str] = []
        self._last_dump_step: Optional[int] = None

    def _detect(self, loss: float, grad_norm: Optional[float],
                nonfinite: int) -> List[Dict[str, Any]]:
        reasons: List[Dict[str, Any]] = []
        if not math.isfinite(loss):
            reasons.append({"reason": "nonfinite_loss",
                            "metric": "loss", "value": repr(loss)})
        elif self._loss.n >= self.warmup:
            z = self._loss.z(loss)
            if z is not None and abs(z) > self.z_threshold:
                reasons.append({"reason": "loss_spike",
                                "metric": "loss", "value": loss,
                                "z": round(z, 2)})
        if nonfinite:
            reasons.append({"reason": "nonfinite_grads",
                            "metric": "nonfinite_leaf_elems",
                            "value": int(nonfinite)})
        if grad_norm is not None:
            if not math.isfinite(grad_norm):
                reasons.append({"reason": "nonfinite_grad_norm",
                                "metric": "grad_norm",
                                "value": repr(grad_norm)})
            elif self._grad.n >= self.warmup:
                z = self._grad.z(grad_norm)
                if z is not None and abs(z) > self.z_threshold:
                    reasons.append({"reason": "grad_spike",
                                    "metric": "grad_norm",
                                    "value": grad_norm,
                                    "z": round(z, 2)})
        return reasons

    def observe(self, step: int, loss: float,
                grad_norm: Optional[float] = None, nonfinite: int = 0,
                signature: Optional[str] = None,
                wall_ms: Optional[float] = None,
                now: Optional[float] = None
                ) -> Optional[Dict[str, Any]]:
        """Feed one step's scalars; returns the anomaly dict when the
        detector fires, else None.  ``now`` is an injectable
        perf_counter timestamp for deterministic tests."""
        loss = float(loss)
        grad_norm = None if grad_norm is None else float(grad_norm)
        nonfinite = int(nonfinite)
        self.recorder.record(
            "train_step", ts=now, step=int(step),
            loss=(round(loss, 6) if math.isfinite(loss)
                  else repr(loss)),
            grad_norm=(None if grad_norm is None else
                       (round(grad_norm, 6)
                        if math.isfinite(grad_norm)
                        else repr(grad_norm))),
            nonfinite=nonfinite,
            **({"wall_ms": round(wall_ms, 3)}
               if wall_ms is not None else {}))
        with self._lock:
            self.observed += 1
            reasons = self._detect(loss, grad_norm, nonfinite)
            if math.isfinite(loss):
                self._loss.update(loss)
            if grad_norm is not None and math.isfinite(grad_norm):
                self._grad.update(grad_norm)
            self._trail.append({
                "step": int(step),
                "loss": loss if math.isfinite(loss) else repr(loss),
                "grad_norm": (grad_norm
                              if grad_norm is None
                              or math.isfinite(grad_norm)
                              else repr(grad_norm)),
                "nonfinite": nonfinite})
            if not reasons:
                return None
            self.anomalies += 1
            first = reasons[0]
            anomaly = {"trainer": self.trainer, "step": int(step),
                       "reason": first["reason"],
                       "metric": first["metric"],
                       "value": first["value"],
                       "reasons": reasons,
                       "signature": signature}
            self.last_anomaly = anomaly
            trail = list(self._trail)
            cooled = (self._last_dump_step is None
                      or int(step) - self._last_dump_step
                      >= self.dump_cooldown)
            if cooled:
                self._last_dump_step = int(step)
        self.recorder.record("train_anomaly", ts=now, step=int(step),
                             reason=first["reason"],
                             metric=first["metric"],
                             value=first["value"])
        if cooled:
            path = self.recorder.dump(
                reason=f"train_anomaly_{first['reason']}",
                context={"trainer": self.trainer, "step": int(step),
                         "reason": first["reason"],
                         "metric": first["metric"],
                         "value": first["value"],
                         "signature": signature,
                         "trail": trail})
            if path:
                with self._lock:
                    self.dumps.append(path)
        return anomaly

    def stats(self) -> Dict[str, Any]:
        """``train_stats()["health"]`` block."""
        with self._lock:
            return {"observed": self.observed,
                    "anomalies": self.anomalies,
                    "last_anomaly": (dict(self.last_anomaly)
                                     if self.last_anomaly else None),
                    "loss": self._loss.stats(),
                    "grad_norm": self._grad.stats(),
                    "z_threshold": self.z_threshold,
                    "dumps": list(self.dumps)}


# ---------------------------------------------------------------------------
# per-trainer singletons
# ---------------------------------------------------------------------------

_trackers: Dict[str, GoodputTracker] = {}
_watchdogs: Dict[str, HealthWatchdog] = {}
_recorders: Dict[str, FlightRecorder] = {}
# reentrant: HealthWatchdog.__init__ resolves its recorder through
# get_train_recorder while get_health_watchdog holds this lock
_singleton_lock = threading.RLock()


def get_goodput_tracker(name: str = "default") -> GoodputTracker:
    with _singleton_lock:
        t = _trackers.get(name)
        if t is None:
            t = _trackers[name] = GoodputTracker(name)
        return t


def get_health_watchdog(name: str = "default", **kwargs: Any
                        ) -> HealthWatchdog:
    with _singleton_lock:
        w = _watchdogs.get(name)
        if w is None:
            w = _watchdogs[name] = HealthWatchdog(name, **kwargs)
        return w


def get_train_recorder(name: str = "default") -> FlightRecorder:
    """The named trainer's flight recorder (``train:{name}`` source) —
    the journal ``train_step``/``train_anomaly``/``ckpt_*`` events
    land in, and the postmortem dump path the watchdog uses."""
    with _singleton_lock:
        r = _recorders.get(name)
        if r is None:
            r = _recorders[name] = FlightRecorder(f"train:{name}")
        return r


def registered_trainers() -> List[str]:
    """Every trainer name that has trainwatch or step-telemetry state
    in THIS process (the dashboard's ``/api/train/stats`` key set)."""
    from ray_tpu.train.telemetry import telemetry_names

    with _singleton_lock:
        names = set(_trackers) | set(_watchdogs) | set(_recorders)
    return sorted(names | set(telemetry_names()))


def trainwatch_blocks(name: str = "default") -> Dict[str, Any]:
    """The ``anatomy``/``goodput``/``health``/``checkpoint``/
    ``flightrec`` blocks ``train_stats()`` merges in — stable-shaped
    even for a trainer that never stepped."""
    tracker = get_goodput_tracker(name)
    return {
        "anatomy": tracker.anatomy(),
        "goodput": tracker.goodput_stats(),
        "health": get_health_watchdog(name).stats(),
        "checkpoint": tracker.checkpoint_stats(),
        "flightrec": get_train_recorder(name).stats(),
    }


def dominant_component(anatomy: Dict[str, Any]) -> Optional[str]:
    """The anatomy leg with the largest mean (None when no steps) —
    ``data_wait_ms`` dominance is the input-bound verdict autopilot
    attribution cites."""
    best, best_mean = None, 0.0
    for comp in ANATOMY_COMPONENTS:
        mean = (anatomy.get(comp) or {}).get("mean")
        if isinstance(mean, (int, float)) and mean > best_mean:
            best, best_mean = comp, float(mean)
    return best


def worker_skew(step_ms_by_worker: Dict[str, float],
                threshold: float = 1.25) -> Dict[str, Any]:
    """Multi-worker straggler detection over per-worker mean step
    times (workers ``session.report`` their ``train_stats()`` up; the
    driver feeds ``{worker: step_time_ms_mean}`` here).  A worker
    slower than ``threshold`` × the median is flagged."""
    vals = {str(k): float(v) for k, v in step_ms_by_worker.items()
            if isinstance(v, (int, float))}
    if not vals:
        return {"workers": 0, "median_ms": None, "max_ms": None,
                "spread": None, "stragglers": [],
                "threshold": threshold}
    ordered = sorted(vals.values())
    mid = len(ordered) // 2
    # true median (even counts average the middles) — upper-middle
    # would let a 2x straggler in a 2-worker fleet BE the median and
    # never flag
    median = (ordered[mid] if len(ordered) % 2
              else (ordered[mid - 1] + ordered[mid]) / 2.0)
    spread = ((ordered[-1] - ordered[0]) / median) if median > 0 else 0.0
    stragglers = sorted(w for w, v in vals.items()
                        if median > 0 and v > threshold * median)
    return {"workers": len(vals), "median_ms": round(median, 3),
            "max_ms": round(ordered[-1], 3),
            "spread": round(spread, 4), "stragglers": stragglers,
            "threshold": threshold}


# ---------------------------------------------------------------------------
# the step wrapper build_train_step / grad_accum compose in
# ---------------------------------------------------------------------------

def instrument_trainwatch(step_fn: Callable, *,
                          tracker: Optional[GoodputTracker] = None,
                          watchdog: Optional[HealthWatchdog] = None,
                          trainer: str = "default",
                          batch_arg: int = 2,
                          health_index: int = 3) -> Callable:
    """Wrap a (jitted) train step with anatomy/goodput recording and,
    when ``watchdog`` is given, per-step health observation.

    Without a watchdog the wrapper adds one ``perf_counter`` pair and
    a dict append — no syncs, preserving async dispatch.  With one,
    it ``device_get``s the small health pytree the step returns at
    ``out[health_index]`` (three scalars), which fences the step —
    the deliberate per-step host fence health mode buys its
    detection latency with, and what makes the ``device_compute``
    anatomy leg true device time."""
    tracker = tracker or get_goodput_tracker(trainer)
    seen: set = set()
    counter = [0]

    @functools.wraps(step_fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if not tracker.enabled:
            return step_fn(*args, **kwargs)
        from ray_tpu.train.telemetry import _batch_signature

        batch = args[batch_arg] if len(args) > batch_arg else None
        sig = None
        if batch is not None:
            try:
                sig = _batch_signature(batch)
            except Exception:  # noqa: BLE001 - exotic batch types
                sig = None
        fresh = sig is not None and sig not in seen
        if fresh:
            seen.add(sig)
        t0 = time.perf_counter()
        out = step_fn(*args, **kwargs)
        health = None
        if watchdog is not None and isinstance(out, tuple) \
                and len(out) > health_index:
            import jax

            # the per-step fence: 3 scalars D2H, counted inside the
            # step window so the device leg is real compute time
            health = jax.device_get(out[health_index])
        call_s = time.perf_counter() - t0
        tracker.record_step(call_s, compiled=fresh)
        step_idx = counter[0]
        counter[0] += 1
        if health is not None:
            watchdog.observe(
                step_idx, float(health.get("loss", float("nan"))),
                grad_norm=(float(health["grad_norm"])
                           if "grad_norm" in health else None),
                nonfinite=int(health.get("nonfinite", 0)),
                signature=str(sig) if sig is not None else None,
                wall_ms=call_s * 1e3)
        return out

    wrapped.__wrapped__ = getattr(step_fn, "__wrapped__", step_fn)
    wrapped.goodput = tracker
    wrapped.watchdog = watchdog
    return wrapped
