"""Backend plugin interface (reference analog: train/_internal/backend.py
Backend/BackendConfig — per-framework process-group setup hooks)."""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.train._internal.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks called by BackendExecutor around the worker group's life."""

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig) -> None:
        pass
