"""HuggingFaceTrainer — transformers.Trainer on the train worker gang.

Reference analog: python/ray/train/huggingface/huggingface_trainer.py
(HuggingFaceTrainer): the user supplies ``trainer_init_per_worker``
building a ``transformers.Trainer``; each ray_tpu train worker runs it
under the gloo process group TorchTrainer already establishes (so
transformers' own DDP integration sees a normal distributed env), log
lines stream back through ``session.report``, and the final model is
captured as an AIR checkpoint.

This is the CPU/torch side of the stack — TPU training goes through
JaxTrainer; this trainer exists so transformers users can land on the
same Trainer/Tuner surface (the reference keeps both for the same
reason).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.torch_backend import TorchConfig, TorchTrainer


class HuggingFaceTrainer(TorchTrainer):
    """Run a user-built transformers.Trainer per worker.

    trainer_init_per_worker(config) -> transformers.Trainer; its
    TrainingArguments control epochs/batching/logging.  Rank-0 saves
    the trained model into the AIR checkpoint directory."""

    def __init__(self, trainer_init_per_worker: Callable, *,
                 trainer_init_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config=None, run_config=None,
                 datasets=None, resume_from_checkpoint=None):

        def loop(config: Dict[str, Any]):
            import transformers

            from ray_tpu.air import session
            from ray_tpu.air.checkpoint import Checkpoint

            hf_trainer = trainer_init_per_worker(config)
            if not isinstance(hf_trainer, transformers.Trainer):
                raise TypeError(
                    "trainer_init_per_worker must return a "
                    f"transformers.Trainer, got {type(hf_trainer)}")

            class _ReportCallback(transformers.TrainerCallback):
                def on_log(self, args, state, control, logs=None,
                           **kwargs):
                    if logs:
                        session.report({**logs,
                                        "step": state.global_step})

            hf_trainer.add_callback(_ReportCallback())
            result = hf_trainer.train()
            metrics = dict(result.metrics or {})
            checkpoint = None
            if session.get_world_rank() == 0:
                out_dir = os.path.join(
                    tempfile.mkdtemp(prefix="raytpu_hf_"), "model")
                hf_trainer.save_model(out_dir)
                checkpoint = Checkpoint.from_directory(out_dir)
            session.report(metrics, checkpoint=checkpoint)

        super().__init__(
            loop, train_loop_config=trainer_init_config or {},
            torch_config=torch_config, scaling_config=scaling_config,
            run_config=run_config, datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
