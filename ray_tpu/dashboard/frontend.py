"""Embedded dashboard frontend: a single-file vanilla-JS SPA.

Reference analog: ``dashboard/client/src`` (the React app).  This build
deliberately ships a zero-dependency single file served by the Python
backend — same information surface (overview, nodes, actors, tasks,
placement groups, jobs with log viewer, serve applications, events,
raw metrics), tab navigation, auto-refresh with pause, client-side
filtering — without a node/webpack toolchain in the image.
"""

INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title><style>
:root{--bg:#f6f7f9;--card:#fff;--line:#dfe3e8;--ink:#1c2430;
--dim:#6b7687;--ok:#0a7d24;--bad:#c02020;--warn:#a15c00;--acc:#2458c5}
*{box-sizing:border-box}
body{font-family:system-ui,-apple-system,sans-serif;margin:0;
background:var(--bg);color:var(--ink)}
header{display:flex;align-items:center;gap:1rem;padding:.6rem 1.2rem;
background:var(--card);border-bottom:1px solid var(--line);
position:sticky;top:0;z-index:5}
header h1{font-size:1.05rem;margin:0}
nav{display:flex;gap:.25rem;flex-wrap:wrap}
nav button{border:1px solid var(--line);background:var(--card);
padding:.3rem .7rem;border-radius:6px;cursor:pointer;font-size:.85rem}
nav button.active{background:var(--acc);color:#fff;border-color:var(--acc)}
#ctl{margin-left:auto;display:flex;gap:.5rem;align-items:center;
font-size:.8rem;color:var(--dim)}
main{padding:1rem 1.2rem;max-width:1200px}
.cards{display:flex;gap:.8rem;flex-wrap:wrap;margin-bottom:1rem}
.card{background:var(--card);border:1px solid var(--line);
border-radius:8px;padding:.7rem 1rem;min-width:130px}
.card .k{font-size:.75rem;color:var(--dim)} .card .v{font-size:1.3rem}
table{border-collapse:collapse;width:100%;background:var(--card);
border:1px solid var(--line);border-radius:8px;overflow:hidden}
th,td{border-bottom:1px solid var(--line);padding:5px 9px;
font-size:.82rem;text-align:left;vertical-align:top}
th{background:#eef1f5;font-weight:600;cursor:default}
tr:hover td{background:#f4f7fb}
.ALIVE,.RUNNING,.SUCCEEDED,.CREATED,.ok{color:var(--ok)}
.DEAD,.FAILED,.ERROR,.bad{color:var(--bad)}
.PENDING_CREATION,.RESTARTING,.PENDING,.WARNING{color:var(--warn)}
.bar{height:8px;background:#e6eaf0;border-radius:4px;min-width:90px}
.bar i{display:block;height:100%;background:var(--acc);border-radius:4px}
input[type=search]{border:1px solid var(--line);border-radius:6px;
padding:.3rem .6rem;font-size:.85rem;width:230px;margin-bottom:.6rem}
pre{background:#10151d;color:#dce3ee;padding: .8rem;border-radius:8px;
font-size:.78rem;overflow:auto;max-height:480px}
#err{color:var(--bad);font-size:.85rem}
a.jlog{color:var(--acc);cursor:pointer;text-decoration:underline}
.mono{font-family:ui-monospace,monospace;font-size:.78rem}
</style></head><body>
<header><h1>ray_tpu</h1><nav id=nav></nav>
<div id=ctl><span id=clock></span>
<label><input type=checkbox id=auto checked> auto-refresh</label>
<span id=err></span></div></header>
<main id=main></main>
<script>
const VIEWS=['overview','nodes','actors','tasks','placement groups',
             'jobs','serve','events','metrics'];
let view='overview', logsFor=null, filter='', gen=0;
const $=s=>document.querySelector(s);
const esc=s=>String(s).replace(/[&<>"']/g,c=>({'&':'&amp;','<':'&lt;',
 '>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const fmtRes=r=>esc(Object.entries(r||{})
  .filter(([k])=>!k.startsWith('node:'))
  .map(([k,v])=>`${k}:${+(+v).toFixed(2)}`).join(' '));
const cls=s=>`<span class="${esc(s)}">${esc(s)}</span>`;
function nav(){const n=$('#nav');n.innerHTML='';
 for(const v of VIEWS){const b=document.createElement('button');
  b.textContent=v;b.className=v===view?'active':'';
  b.onclick=()=>{view=v;logsFor=null;filter='';render()};
  n.appendChild(b)}}
async function j(u){const r=await fetch(u);
 if(!r.ok)throw new Error(u+' -> '+r.status);return r.json()}
function card(k,v,c){return `<div class=card><div class=k>${esc(k)}</div>
 <div class="v ${esc(c||'')}">${v}</div></div>`}
function table(heads,rows){
 return `<table><tr>${heads.map(h=>`<th>${esc(h)}</th>`).join('')}</tr>
 ${rows.map(r=>`<tr>${r.map(c=>`<td>${c}</td>`).join('')}</tr>`).join('')}
 </table>`}
function searchBox(ph){return `<input type=search id=flt value="${esc(filter)}"
 placeholder="filter ${esc(ph)}..."
 oninput="filter=this.value;render(false,true)">`}
// filter on RAW record values (never on generated markup)
function flt(recs){if(!filter)return recs;const f=filter.toLowerCase();
 return recs.filter(r=>r.raw.join(' ').toLowerCase().includes(f))}
const rows=recs=>recs.map(r=>r.html);
function bar(used,total){const p=total?Math.min(100,100*used/total):0;
 return `<div class=bar title="${used.toFixed(1)}/${total}">
 <i style="width:${p}%"></i></div>`}

const renderers={
 async overview(){
  const [s,nodes]=await Promise.all([j('/api/summary'),j('/api/nodes')]);
  const cpuT=nodes.reduce((a,n)=>a+(n.resources.CPU||0),0);
  const cpuF=nodes.reduce((a,n)=>a+(n.available.CPU||0),0);
  const actors=Object.entries(s.actors.by_state)
   .map(([k,v])=>card('actors '+k,v,k)).join('');
  return `<div class=cards>
   ${card('nodes',nodes.length)}${card('tasks finished',s.tasks.total)}
   ${card('CPU in use',(cpuT-cpuF).toFixed(1)+' / '+cpuT)}
   ${actors}</div>
   <h3>Cluster resources</h3>${table(
    ['node','alive','utilization','total','available'],
    nodes.map(n=>[esc(n.node_id.slice(0,12)),
     cls(n.alive?'ALIVE':'DEAD'),
     bar((n.resources.CPU||0)-(n.available.CPU||0),n.resources.CPU||0),
     fmtRes(n.resources),fmtRes(n.available)]))}`},
 async nodes(){const nodes=await cj('/api/nodes');
  const recs=nodes.map(n=>({raw:[n.node_id,n.alive?'alive':'dead',
    n.address||''],html:[`<span class=mono>${esc(n.node_id)}</span>`,
    cls(n.alive?'ALIVE':'DEAD'),esc(n.address||''),
    fmtRes(n.resources),fmtRes(n.available)]}));
  return searchBox('nodes')+table(
   ['node id','alive','address','total','available'],rows(flt(recs)))},
 async actors(){const a=await cj('/api/actors');
  const recs=a.map(x=>({raw:[x.actor_id,x.name||'',x.state],
   html:[`<span class=mono>${esc(x.actor_id.slice(0,16))}</span>`,
    esc(x.name||''),cls(x.state),
    esc(x.node_id?x.node_id.slice(0,12):''),
    String(x.num_restarts),fmtRes(x.resources)]}));
  return searchBox('actors')+table(
   ['actor id','name','state','node','restarts','resources'],
   rows(flt(recs)))},
 async tasks(){const t=await cj('/api/tasks');
  const recs=t.slice(-500).reverse().map(x=>({
   raw:[x.name||x.task_id,x.actor_id?'actor':'task'],
   html:[esc(x.name||x.task_id.slice(0,16)),
    x.actor_id?'actor task':'task',
    x.end&&x.start?((x.end-x.start)*1000).toFixed(1):'',
    esc(x.worker_id?x.worker_id.slice(0,12):''),
    String(x.pid||'')]}));
  return searchBox('tasks')+table(
   ['task','kind','duration (ms)','worker','pid'],rows(flt(recs)))},
 async 'placement groups'(){const p=await j('/api/placement_groups');
  return table(['pg id','name','state','strategy','bundles'],
   p.map(x=>[`<span class=mono>${esc(x.pg_id.slice(0,16))}</span>`,
    esc(x.name||''),cls(x.state),esc(x.strategy),
    esc(JSON.stringify(x.bundles))]))},
 async jobs(){
  if(logsFor!==null){
   const lg=await j('/api/jobs/'+encodeURIComponent(logsFor)+'/logs');
   return `<a class=jlog id=back>&larr; jobs</a>
    <h3>logs: ${esc(logsFor)}</h3><pre>${esc(lg.logs||'(empty)')}</pre>`}
  const jobs=lastJobs;  // fetched by render() for the click handlers
  return table(['job id','status','entrypoint','logs'],
   jobs.map((x,i)=>[`<span class=mono>${esc(x.job_id)}</span>`,
    cls(x.status),esc(x.entrypoint||''),
    `<a class=jlog data-i="${i}">view</a>`]))},
 async serve(){const s=await j('/api/serve/applications');
  const deps=Object.entries(s.applications||{});
  return table(['deployment','status','replicas','autoscaling','route'],
   deps.map(([name,d])=>[esc(name),
    `<span class="${d.status==='HEALTHY'?'ok':'bad'}">`+
    `${esc(d.status||'')}</span>`,
    `${d.replicas||0} / ${d.target_replicas||0}`,
    d.autoscaling?'yes':'no',esc(d.route||'')]))},
 async events(){const ev=await cj('/api/events?limit=200');
  const recs=ev.map(e=>({raw:[e.severity,e.source,e.message],
   html:[new Date(e.timestamp*1000).toLocaleTimeString(),
    cls(e.severity),esc(e.source),esc(e.message)]}));
  return searchBox('events')+table(
   ['time','severity','source','message'],rows(flt(recs)))},
 async metrics(){const r=await fetch('/metrics');
  if(!r.ok)throw new Error('/metrics -> '+r.status);
  return `<pre>${esc(await r.text())}</pre>`},
};
let lastJobs=[];
// per-view data cache: filter keystrokes re-render from it instead of
// re-downloading the full list on every character
const cache={};
let useCache=false;
async function cj(u){if(useCache&&cache[u])return cache[u];
 const d=await j(u);cache[u]=d;return d}
async function render(renav=true,fromFilter=false){if(renav)nav();
 const myGen=++gen;
 useCache=fromFilter;
 try{$('#err').textContent='';
  if(view==='jobs'&&logsFor===null)
   lastJobs=await j('/api/jobs');
  const html=await renderers[view]();
  if(myGen!==gen)return;  // a newer render superseded this fetch
  const fltEl=$('#flt'), pos=fltEl?fltEl.selectionStart:null;
  $('#main').innerHTML=html;
  // delegated (never inline) handlers: job ids are untrusted data
  $('#main').querySelectorAll('a.jlog[data-i]').forEach(a=>{
   a.onclick=()=>{const job=lastJobs[+a.dataset.i];
    if(job){logsFor=job.job_id;render()}}});
  const back=$('#back'); if(back)back.onclick=()=>{logsFor=null;render()};
  if(pos!==null&&$('#flt')){$('#flt').focus();
   $('#flt').setSelectionRange(pos,pos)}
 }catch(e){if(myGen===gen)$('#err').textContent=String(e)}}
setInterval(()=>{ $('#clock').textContent=new Date().toLocaleTimeString();
 if($('#auto').checked&&document.activeElement!==$('#flt'))
  render(false)},3000);
render();
</script></body></html>
"""
