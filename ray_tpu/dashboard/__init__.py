"""Dashboard-lite (reference analog: dashboard/ head + modules): a JSON
state API + Prometheus metrics endpoint over aiohttp."""

from ray_tpu.dashboard.app import start_dashboard

__all__ = ["start_dashboard"]
