"""Dashboard server: cluster state as JSON + Prometheus text metrics.

Reference analog: dashboard/head.py:62 DashboardHead (+ the metrics
agent's Prometheus re-export, _private/metrics_agent.py:93).  One aiohttp
server inside a detached actor:

  GET /api/nodes | /api/actors | /api/tasks | /api/placement_groups
  GET /api/summary
  GET /metrics          (Prometheus text format)
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

DASHBOARD_NAME = "RAYTPU_DASHBOARD"


def _merged_programs():
    """Fleet-wide program view: every live deployment's engine_stats()
    "programs" block merged over this process's own (mostly empty)
    registry — on a name collision the busiest replica view wins.
    Shared by /api/perf/programs and /api/perf/autopilot.  Returns
    (programs, per_deployment_blocks, devices)."""
    from ray_tpu._private import device_stats as ds

    devices = ds.device_memory_stats()
    programs = ds.get_registry().snapshot(
        n_devices=max(1, len(devices)))
    per_dep = {}
    try:
        from ray_tpu.serve import api as serve_api

        for name in serve_api.status():
            try:
                stats = serve_api.engine_stats(name, timeout=15)
            except Exception:  # noqa: BLE001 - no stats
                continue
            blocks = stats.get("programs")
            if not isinstance(blocks, dict):
                continue
            per_dep[name] = blocks
            for prog, blk in blocks.items():
                cur = programs.get(prog)
                if (cur is None or blk.get(
                        "compile_events", 0) >= cur.get(
                        "compile_events", 0)):
                    programs[prog] = blk
    except Exception:  # noqa: BLE001 - serve not running
        pass
    return programs, per_dep, devices


class DashboardActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dashboard")
        self._thread.start()
        self._started.wait(timeout=30)
        self._write_prom_service_discovery()

    def _write_prom_service_discovery(self) -> None:
        """Prometheus file-based service discovery (reference:
        _private/metrics_agent.py:340 PrometheusServiceDiscoveryWriter):
        point prometheus at
        <session_dir>/prom_metrics_service_discovery.json via
        file_sd_configs and it scrapes the cluster's /metrics."""
        import json
        import os

        from ray_tpu._private import worker_context

        node = worker_context.node()
        # the dashboard usually runs as a remote actor: no Node object in
        # this process, but every worker carries the session dir in env
        session_dir = (node.session_dir if node is not None
                       else os.environ.get("RAYTPU_SESSION_DIR", ""))
        if not session_dir:
            return
        path = os.path.join(session_dir,
                            "prom_metrics_service_discovery.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump([{
                    "labels": {"job": "ray_tpu"},
                    "targets": [f"{self.host}:{self.port}"],
                }], f)
            os.replace(tmp, path)  # atomic: prometheus may be reading
        except OSError:
            pass

    def _state(self):
        from ray_tpu.util import state

        return state

    def _metrics_text(self) -> str:
        from ray_tpu.util import state

        lines = []
        nodes = state.list_nodes()
        lines.append("# TYPE raytpu_nodes gauge")
        lines.append(f"raytpu_nodes {sum(n['alive'] for n in nodes)}")
        for n in nodes:
            nid = n["node_id"][:12]
            for res, total in n["resources"].items():
                avail = n["available"].get(res, 0.0)
                name = res.lower().replace("-", "_")
                lines.append(
                    f'raytpu_resource_total{{node="{nid}",resource='
                    f'"{name}"}} {total}')
                lines.append(
                    f'raytpu_resource_available{{node="{nid}",resource='
                    f'"{name}"}} {avail}')
        actors = state.summarize_actors()
        lines.append("# TYPE raytpu_actors gauge")
        for st, count in actors["by_state"].items():
            lines.append(f'raytpu_actors{{state="{st}"}} {count}')
        tasks = state.summarize_tasks()
        lines.append("# TYPE raytpu_tasks_finished_total counter")
        lines.append(f"raytpu_tasks_finished_total {tasks['total']}")
        lines.append("# TYPE raytpu_task_execution_seconds_total counter")
        lines.append(f"raytpu_task_execution_seconds_total "
                     f"{tasks['total_execution_s']}")
        # Application-defined metrics published by every process
        # (ray_tpu.util.metrics -> GCS KV snapshots).
        from ray_tpu._private import worker_context
        from ray_tpu.util.metrics import collect_cluster_metrics

        cw = worker_context.maybe_core_worker()
        if cw is not None:
            try:
                lines.extend(collect_cluster_metrics(cw.kv_get,
                                                     cw.kv_keys))
            except Exception:  # noqa: BLE001 - metrics must not 500
                pass
        return "\n".join(lines) + "\n"

    def _serve(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        state = self._state()

        def j(fn):
            async def handler(_req):
                data = await loop.run_in_executor(None, fn)
                return web.json_response(data)

            return handler

        async def metrics(_req):
            text = await loop.run_in_executor(None, self._metrics_text)
            return web.Response(text=text,
                                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/api/nodes", j(state.list_nodes))
        app.router.add_get("/api/actors", j(state.list_actors))
        app.router.add_get("/api/tasks", j(state.list_tasks))
        app.router.add_get("/api/placement_groups",
                           j(state.list_placement_groups))
        app.router.add_get("/api/summary", j(lambda: {
            "tasks": state.summarize_tasks(),
            "actors": state.summarize_actors(),
            "nodes": len(state.list_nodes())}))
        app.router.add_get("/metrics", metrics)

        # Job submission REST (reference: dashboard/modules/job routes).
        from dataclasses import asdict

        from ray_tpu import job as job_api

        async def jobs_submit(req):
            body = await req.json()
            jid = await loop.run_in_executor(
                None, lambda: job_api.submit_job(
                    body["entrypoint"],
                    runtime_env=body.get("runtime_env"),
                    metadata=body.get("metadata"),
                    job_id=body.get("job_id")))
            return web.json_response({"job_id": jid})

        async def jobs_list(_req):
            jobs = await loop.run_in_executor(None, job_api.list_jobs)
            return web.json_response([asdict(i) for i in jobs])

        async def jobs_status(req):
            info = await loop.run_in_executor(
                None, lambda: job_api.get_job_info(
                    req.match_info["job_id"]))
            return web.json_response(asdict(info))

        async def jobs_logs(req):
            text = await loop.run_in_executor(
                None, lambda: job_api.get_job_logs(
                    req.match_info["job_id"]))
            return web.json_response({"logs": text})

        async def jobs_stop(req):
            ok = await loop.run_in_executor(
                None, lambda: job_api.stop_job(req.match_info["job_id"]))
            return web.json_response({"stopped": ok})

        app.router.add_post("/api/jobs", jobs_submit)
        app.router.add_get("/api/jobs", jobs_list)
        app.router.add_get("/api/jobs/{job_id}", jobs_status)
        app.router.add_get("/api/jobs/{job_id}/logs", jobs_logs)
        app.router.add_post("/api/jobs/{job_id}/stop", jobs_stop)

        # Declarative serve REST (reference: dashboard serve module,
        # PUT /api/serve/applications/ consuming ServeApplicationSchema).
        async def serve_apply(req):
            from ray_tpu.serve import schema as serve_schema

            body = await req.json()
            try:
                await loop.run_in_executor(
                    None, lambda: serve_schema.apply(body))
            except (ValueError, TypeError, KeyError, AttributeError,
                    ImportError) as e:
                # config/validation-class errors (bad types, unknown
                # import paths) are the CLIENT's fault: 400, not 500
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=400)
            return web.json_response(
                await loop.run_in_executor(None, serve_schema.status))

        async def serve_status(_req):
            from ray_tpu.serve import schema as serve_schema

            return web.json_response(
                await loop.run_in_executor(None, serve_schema.status))

        app.router.add_put("/api/serve/applications", serve_apply)
        app.router.add_get("/api/serve/applications", serve_status)

        # Engine telemetry aggregation (serve/telemetry.py): one
        # engine_stats() snapshot per deployment whose replicas expose
        # it (LM engines); others report the reason they were skipped.
        async def serve_stats(_req):
            def _collect():
                from ray_tpu.serve import api as serve_api

                out = {}
                try:
                    deployments = serve_api.status()
                except Exception:  # noqa: BLE001 - serve not running
                    return out
                for name in deployments:
                    try:
                        out[name] = serve_api.engine_stats(name,
                                                           timeout=15)
                    except Exception as e:  # noqa: BLE001 - no stats
                        out[name] = {
                            "error": f"{type(e).__name__}: {e}"[:300]}
                return out

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/serve/stats", serve_stats)

        # SLO burn rates + flight-recorder occupancy (serve/slo.py,
        # _private/flightrec.py): the "slo"/"flightrec" blocks of each
        # deployment's engine_stats(), without the heavyweight rest —
        # the poll target for burn-rate dashboards and autoscalers.
        async def serve_slo(_req):
            def _collect():
                from ray_tpu.serve import api as serve_api

                out = {}
                try:
                    deployments = serve_api.status()
                except Exception:  # noqa: BLE001 - serve not running
                    return out
                for name in deployments:
                    try:
                        stats = serve_api.engine_stats(name,
                                                       timeout=15)
                        out[name] = {
                            "slo": stats.get("slo"),
                            "flightrec": stats.get("flightrec"),
                        }
                    except Exception as e:  # noqa: BLE001 - no stats
                        out[name] = {
                            "error": f"{type(e).__name__}: {e}"[:300]}
                return out

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/serve/slo", serve_slo)

        # kvscope (serve/kvscope.py): each deployment's "kv_scope"
        # block — KV pool occupancy ring, eviction forensics, HBM
        # ledger — without the heavyweight rest.  The dump feeds
        # `python -m ray_tpu.tools.kvscope report/timeline/export`
        # directly.
        async def serve_kvscope(_req):
            def _collect():
                from ray_tpu.serve import api as serve_api

                out = {}
                try:
                    deployments = serve_api.status()
                except Exception:  # noqa: BLE001 - serve not running
                    return out
                for name in deployments:
                    try:
                        stats = serve_api.engine_stats(name,
                                                       timeout=15)
                        out[name] = {
                            "kv_scope": stats.get("kv_scope"),
                            "kv_tier": stats.get("kv_tier"),
                        }
                    except Exception as e:  # noqa: BLE001 - no stats
                        out[name] = {
                            "error": f"{type(e).__name__}: {e}"[:300]}
                return out

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/serve/kvscope", serve_kvscope)

        # Fleet control plane (serve/router.py): every live
        # build_llm_fleet() in this process — routing policy mix,
        # pooled prefix hit rate, per-tenant SLO attainment, and the
        # autoscaler's current signals, keyed by fleet name.  The
        # document's "health" block is also served standalone at
        # /api/serve/health for liveness pollers.
        async def serve_fleet(_req):
            def _collect():
                from ray_tpu.serve.router import fleet_registry

                out = {}
                for name, fleet in fleet_registry().items():
                    try:
                        out[name] = fleet.fleet_stats()
                    except Exception as e:  # noqa: BLE001
                        out[name] = {
                            "error": f"{type(e).__name__}: {e}"[:300]}
                return out

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/serve/fleet", serve_fleet)

        # Healthwatch (serve/health.py): every live fleet's health
        # block only — per-replica liveness state, last-heartbeat age,
        # transition history, and detection latency — the poll target
        # for liveness dashboards.  The full fleet document above
        # (/api/serve/fleet) carries the same block under "health".
        async def serve_health(_req):
            def _collect():
                from ray_tpu.serve.router import fleet_registry

                out = {}
                for name, fleet in fleet_registry().items():
                    try:
                        out[name] = fleet._health_block()
                    except Exception as e:  # noqa: BLE001
                        out[name] = {
                            "error": f"{type(e).__name__}: {e}"[:300]}
                return out

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/serve/health", serve_health)

        # Trainwatch (train/telemetry.py + train/goodput.py): one
        # train_stats() snapshot per trainer that has stepped in THIS
        # process — step-time percentiles plus the anatomy / goodput /
        # health / checkpoint blocks, keyed by trainer name.
        async def train_stats_view(_req):
            def _collect():
                from ray_tpu.train.goodput import registered_trainers
                from ray_tpu.train.telemetry import train_stats

                out = {}
                for name in registered_trainers():
                    try:
                        out[name] = train_stats(name)
                    except Exception as e:  # noqa: BLE001
                        out[name] = {
                            "error": f"{type(e).__name__}: {e}"[:300]}
                return out

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/train/stats", train_stats_view)

        # Tracebus (ray_tpu/tools/tracebus.py): one request's causal
        # span tree — router.route → engine.queue/kv.reserve →
        # engine.prefill (+ matched device program dispatch) →
        # engine.decode — by trace id (full or prefix) or engine-local
        # id.  Fleets are scanned first (their find_request carries
        # the replica name); then every serve deployment exposing
        # request_trace.
        async def serve_trace(req):
            rid = req.match_info["request_id"]

            def _collect():
                from ray_tpu.serve.router import fleet_registry
                from ray_tpu.tools import tracebus

                snap = None
                for fleet in fleet_registry().values():
                    try:
                        snap = fleet.find_request(rid)
                    except Exception:  # noqa: BLE001
                        snap = None
                    if snap is not None:
                        break
                if snap is None:
                    import ray_tpu
                    from ray_tpu.serve import api as serve_api

                    try:
                        deployments = serve_api.status()
                    except Exception:  # noqa: BLE001
                        deployments = {}
                    for name in deployments:
                        try:
                            handle = serve_api.get_deployment_handle(
                                name)
                            snap = ray_tpu.get(
                                handle.method("request_trace")
                                .remote(rid), timeout=15)
                        except Exception:  # noqa: BLE001
                            snap = None
                        if snap is not None:
                            snap.setdefault("replica", name)
                            break
                if snap is None:
                    return None
                spans = tracebus.attach_device_spans(
                    tracebus.build_request_spans(snap), snap,
                    tracebus._device_programs())
                return dict(snap, spans=spans)

            data = await loop.run_in_executor(None, _collect)
            if data is None:
                return web.json_response(
                    {"error": f"request {rid!r} not found"},
                    status=404)
            return web.json_response(data)

        app.router.add_get("/api/serve/trace/{request_id}",
                           serve_trace)

        # Perf observatory (_private/device_stats.py): per-program
        # compiled cost model / recompile watchdog / live MFU, plus
        # per-chip allocator stats — the device-side complement of
        # /api/serve/stats.  Registries are per-process, so the
        # dashboard merges every live deployment's engine_stats()
        # "programs" block over its own (mostly empty) local registry;
        # on a name collision the busiest replica view wins, and the
        # raw per-deployment blocks stay under "deployments".
        async def perf_programs(_req):
            def _collect():
                from ray_tpu._private import device_stats as ds

                programs, per_dep, devices = _merged_programs()
                return {
                    "programs": programs,
                    "deployments": per_dep,
                    "devices": devices,
                    "peak_flops_per_chip": ds.peak_flops_per_chip(),
                }

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/perf/programs", perf_programs)

        # Autopilot (ray_tpu/tools/autopilot): the same merged program
        # view pushed through roofline attribution (which program is
        # the bottleneck, compute- vs HBM-bound) plus the ledger
        # verdict summary and the next planned sweep — the closed
        # tuning loop's state as one JSON document.
        async def perf_autopilot(req):
            budget = int(req.query.get("budget", 8))

            def _collect():
                from ray_tpu.tools.autopilot import (attribution,
                                                     verdict)

                programs, per_dep, _ = _merged_programs()
                # request-side evidence: the tracebus p99 critical
                # path over every live fleet's retained requests
                req_ev = None
                try:
                    from ray_tpu.serve.router import fleet_registry
                    from ray_tpu.tools import tracebus

                    reqs = []
                    for fleet in fleet_registry().values():
                        reqs.extend(fleet.trace_records())
                    if reqs:
                        req_ev = tracebus.request_evidence(
                            {"requests": reqs})
                except Exception:  # noqa: BLE001 - evidence optional
                    req_ev = None
                # memory-side evidence: the pooled kvscope block of
                # any live fleet (cache-thrash waste attribution)
                # plus its host-tier block (churn-absorption credit)
                kv_ev = None
                tier_ev = None
                try:
                    from ray_tpu.serve.router import fleet_registry

                    for fleet in fleet_registry().values():
                        fs = fleet.fleet_stats()
                        ks = fs.get("kv_scope")
                        kt = fs.get("kv_tier")
                        if ks and (ks.get("reprefill_waste_frac")
                                   or (kt or {}).get("tokens_restored")):
                            kv_ev = ks
                            if kt and kt.get("enabled"):
                                tier_ev = kt
                            break
                except Exception:  # noqa: BLE001 - evidence optional
                    kv_ev = None
                    tier_ev = None
                att = attribution.attribute(
                    programs, request_anatomy=req_ev, kv_scope=kv_ev,
                    kv_tier=tier_ev)
                try:
                    v = verdict.build_verdict(budget=budget,
                                              attribution=att)
                except Exception as e:  # noqa: BLE001 - no ledger
                    v = {"error": f"{type(e).__name__}: {e}"[:300],
                         "attribution": att}
                v["deployments"] = sorted(per_dep)
                return v

            return web.json_response(
                await loop.run_in_executor(None, _collect))

        app.router.add_get("/api/perf/autopilot", perf_autopilot)

        # On-demand profiler capture (util/state.py profile_device):
        # POST {"logdir": ..., "seconds": 1.0} traces this process for
        # the window and returns where the trace landed.  Degrades to
        # {"ok": false} where jax.profiler is unavailable — same no-op
        # contract as profile_device itself.
        async def perf_profile(req):
            try:
                body = await req.json()
            except Exception:  # noqa: BLE001 - empty body is fine
                body = {}
            logdir = str(body.get("logdir", "/tmp/raytpu_profile"))
            seconds = min(60.0, max(0.0,
                                    float(body.get("seconds", 1.0))))

            def _capture():
                import time as _time

                from ray_tpu.util.state import profile_device

                with profile_device(logdir) as prof:
                    _time.sleep(seconds)
                return bool(prof._active)

            ok = await loop.run_in_executor(None, _capture)
            return web.json_response(
                {"ok": ok, "logdir": logdir, "seconds": seconds})

        app.router.add_post("/api/perf/profile", perf_profile)

        # Structured events (reference: dashboard event module consuming
        # RAY_EVENT files, src/ray/util/event.h:41).
        async def events_list(req):
            from ray_tpu._private import events as ev

            recs = await loop.run_in_executor(
                None, lambda: ev.read_events(
                    limit=int(req.query.get("limit", 200)),
                    severity=req.query.get("severity"),
                    source=req.query.get("source")))
            return web.json_response(recs)

        app.router.add_get("/api/events", events_list)

        # Workflow event provider (reference:
        # workflow/http_event_provider.py — external systems POST an
        # event; in-cluster KVEventListeners wake on it).
        async def workflow_post_event(req):
            body = await req.json()
            name = body.get("name")
            if not name:
                return web.json_response(
                    {"error": "missing 'name'"}, status=400)

            def _post():
                from ray_tpu.workflow.events import post_event

                post_event(name, body.get("payload"))

            await loop.run_in_executor(None, _post)
            return web.json_response({"posted": name})

        app.router.add_post("/api/workflows/events", workflow_post_event)

        async def index(_req):
            from ray_tpu.dashboard.frontend import INDEX_HTML

            return web.Response(text=INDEX_HTML,
                                content_type="text/html")

        app.router.add_get("/", index)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        self._started.set()
        loop.run_forever()

    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def ping(self) -> bool:
        return self._started.is_set()


def start_dashboard(port: int = 8265, host: str = "127.0.0.1") -> str:
    """Start (or find) the dashboard actor; returns its URL."""
    import ray_tpu

    ray_tpu._auto_init()
    try:
        actor = ray_tpu.get_actor(DASHBOARD_NAME)
    except Exception:  # noqa: BLE001
        actor = ray_tpu.remote(num_cpus=0.1, lifetime="detached",
                               name=DASHBOARD_NAME)(DashboardActor).remote(
            host, port)
    ray_tpu.get(actor.ping.remote(), timeout=60)
    return ray_tpu.get(actor.address.remote(), timeout=30)
