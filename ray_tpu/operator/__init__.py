"""Kubernetes-style operator: reconcile RayCluster resources into pods.

Role-equivalent of the reference's legacy K8s operator
(``python/ray/ray_operator/operator.py`` reconciling RayCluster CRs) and
the KubeRay pattern it points users at.  TPU-first difference: a worker
group may declare a TPU slice (``accelerator`` + ``topology``) and then
one *replica* = one ICI-connected slice = ``num_hosts`` pods, gang-
created and gang-deleted, each pod told its position in the slice — the
unit of scaling is the slice, never an individual TPU host.
"""

from ray_tpu.operator.crd import (RayClusterSpec, WorkerGroupSpec,
                                  HeadGroupSpec)
from ray_tpu.operator.operator import (RayClusterOperator, PodProvider,
                                       FakePodProvider, Pod)

__all__ = ["RayClusterSpec", "WorkerGroupSpec", "HeadGroupSpec",
           "RayClusterOperator", "PodProvider", "FakePodProvider", "Pod"]
