"""RayClusterOperator: level-triggered reconciliation of pods.

Role-equivalent of the reference's operator loop
(``python/ray/ray_operator/operator.py`` — watch RayCluster CRs, keep
the cluster's processes matching them).  Like a K8s controller it is
level-triggered: ``reconcile()`` compares desired state (the CR) against
observed state (the pod list) and converges one step; crashes/restarts
of the operator lose nothing because all state is re-read each pass.

The pod API is pluggable (``PodProvider``) so tests run against an
in-memory fake (the autoscaler's FakeNodeProvider pattern,
reference ``autoscaler/_private/fake_multi_node/node_provider.py:36``);
a real deployment implements the same five methods with the K8s API.

TPU slices are gang-managed: a TPU worker group's replica is
``num_hosts`` pods created together; if ANY pod of a slice dies the
whole slice is torn down and re-created — a partial slice cannot form
its ICI mesh, so limping along is strictly worse than a clean rebuild
(this is the multi-host analog of gang scheduling; no reference analog).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
from typing import Dict, List, Optional

from ray_tpu.operator.crd import RayClusterSpec, WorkerGroupSpec

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Pod:
    name: str
    cluster: str
    group: str            # "head" or a worker group name
    replica: int          # replica index within the group (slice id)
    host_index: int       # host within the slice (0 for CPU groups)
    num_hosts: int        # slice size this pod belongs to
    status: str = "running"   # pending|running|failed
    env: Dict[str, str] = dataclasses.field(default_factory=dict)


class PodProvider:
    """What the operator needs from the pod substrate (K8s in prod, the
    in-memory fake in tests)."""

    def create_pod(self, pod: Pod) -> None:
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def list_pods(self, cluster: str) -> List[Pod]:
        raise NotImplementedError


class FakePodProvider(PodProvider):
    """In-memory pod substrate for tests; pods can be failed manually to
    exercise the repair path."""

    def __init__(self):
        self._pods: Dict[str, Pod] = {}
        self._lock = threading.Lock()
        self.created: List[str] = []
        self.deleted: List[str] = []

    def create_pod(self, pod: Pod) -> None:
        with self._lock:
            self._pods[pod.name] = pod
            self.created.append(pod.name)

    def delete_pod(self, name: str) -> None:
        with self._lock:
            self._pods.pop(name, None)
            self.deleted.append(name)

    def list_pods(self, cluster: str) -> List[Pod]:
        with self._lock:
            return [p for p in self._pods.values() if p.cluster == cluster]

    def fail_pod(self, name: str) -> None:
        with self._lock:
            if name in self._pods:
                self._pods[name].status = "failed"


class RayClusterOperator:
    def __init__(self, provider: PodProvider):
        self.provider = provider
        self._specs: Dict[str, RayClusterSpec] = {}

    # -- CR events (what a K8s watch would deliver) -----------------------

    def apply(self, cr_or_spec) -> None:
        spec = (cr_or_spec if isinstance(cr_or_spec, RayClusterSpec)
                else RayClusterSpec.from_dict(cr_or_spec))
        self._specs[spec.name] = spec

    def delete(self, name: str) -> None:
        self._specs.pop(name, None)

    # -- reconciliation ----------------------------------------------------

    def reconcile(self) -> int:
        """One level-triggered pass over every known cluster; returns the
        number of pod create/delete actions taken."""
        actions = 0
        seen_clusters = set()
        for spec in list(self._specs.values()):
            seen_clusters.add(spec.name)
            try:
                actions += self._reconcile_cluster(spec)
            except Exception:  # noqa: BLE001 - one cluster's failure
                # must not starve the others; level-triggering retries it
                logger.exception("operator: reconcile of %s failed",
                                 spec.name)
        return actions + self._gc_removed_clusters(seen_clusters)

    def _gc_removed_clusters(self, live: set) -> int:
        """Garbage-collect pods of clusters whose CR was deleted (the
        operator remembers every cluster it has ever reconciled; a real
        K8s provider would label-select instead)."""
        actions = 0
        for name in list(getattr(self, "_ever_seen", set()) - live):
            for pod in self.provider.list_pods(name):
                self.provider.delete_pod(pod.name)
                actions += 1
        self._ever_seen = getattr(self, "_ever_seen", set()) | live
        return actions

    def _reconcile_cluster(self, spec: RayClusterSpec) -> int:
        actions = 0
        pods = self.provider.list_pods(spec.name)
        by_group: Dict[str, List[Pod]] = {}
        for p in pods:
            by_group.setdefault(p.group, []).append(p)

        # head: exactly one, repaired before anything else (workers can't
        # register without it).  Deletion and recreation never happen in
        # the same pass: a real pod API deletes asynchronously, so
        # recreating the same name immediately would conflict — the next
        # level-triggered pass creates it once the name is free.
        head_pods = [p for p in by_group.get("head", [])]
        deleted_head = False
        for p in head_pods:
            if p.status == "failed":
                self.provider.delete_pod(p.name)
                deleted_head = True
                actions += 1
        head_pods = [p for p in head_pods if p.status != "failed"]
        if not head_pods and not deleted_head:
            self.provider.create_pod(Pod(
                name=f"{spec.name}-head", cluster=spec.name, group="head",
                replica=0, host_index=0, num_hosts=1,
                env={"RAY_TPU_ROLE": "head"}))
            actions += 1
        elif len(head_pods) > 1:
            for p in head_pods[1:]:
                self.provider.delete_pod(p.name)
                actions += 1

        for g in spec.worker_groups:
            actions += self._reconcile_group(spec, g,
                                             by_group.get(g.name, []))

        # pods whose group vanished from the CR
        group_names = {"head"} | {g.name for g in spec.worker_groups}
        for p in pods:
            if p.group not in group_names:
                self.provider.delete_pod(p.name)
                actions += 1
        return actions

    def _reconcile_group(self, spec: RayClusterSpec, g: WorkerGroupSpec,
                         pods: List[Pod]) -> int:
        actions = 0
        want_replicas = g.clamped_replicas()
        hosts = g.num_hosts

        # group pods by replica (slice); a slice with any failed or
        # missing pod is torn down whole (ICI gang semantics)
        by_replica: Dict[int, List[Pod]] = {}
        for p in pods:
            by_replica.setdefault(p.replica, []).append(p)
        healthy: List[int] = []
        tore_down = False
        for rid, rpods in sorted(by_replica.items()):
            ok = (len(rpods) == hosts
                  and all(p.status != "failed" for p in rpods))
            if ok:
                healthy.append(rid)
            else:
                for p in rpods:
                    self.provider.delete_pod(p.name)
                    actions += 1
                tore_down = True
                logger.info("operator: tearing down unhealthy slice "
                            "%s/%s replica %d", spec.name, g.name, rid)

        # scale down: delete newest healthy slices first
        while len(healthy) > want_replicas:
            rid = healthy.pop()
            for p in by_replica[rid]:
                self.provider.delete_pod(p.name)
                actions += 1

        # scale up: create whole slices at free replica indices.  Skipped
        # on a pass that tore slices down — pod deletion is asynchronous
        # on a real substrate, so the replacement (which reuses the same
        # pod names) waits for the next pass.
        if tore_down:
            return actions
        free_ids = (i for i in itertools.count() if i not in healthy)
        while len(healthy) < want_replicas:
            rid = next(free_ids)
            for host in range(hosts):
                env = {"RAY_TPU_ROLE": "worker",
                       "RAY_TPU_GROUP": g.name,
                       "RAY_TPU_REPLICA": str(rid)}
                if g.accelerator:
                    # each pod learns its slice position — the operator's
                    # analog of TPU_WORKER_ID/TPU_WORKER_HOSTNAMES that
                    # jax.distributed bootstrap consumes
                    env.update({
                        "TPU_WORKER_ID": str(host),
                        "TPU_ACCELERATOR_TYPE": g.accelerator,
                        "TPU_TOPOLOGY": g.topology,
                        "TPU_HOSTS_PER_SLICE": str(hosts),
                    })
                self.provider.create_pod(Pod(
                    name=f"{spec.name}-{g.name}-{rid}-{host}",
                    cluster=spec.name, group=g.name, replica=rid,
                    host_index=host, num_hosts=hosts, env=env))
                actions += 1
            healthy.append(rid)
        return actions
