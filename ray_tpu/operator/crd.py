"""RayCluster custom-resource schema (operator's desired state).

Mirrors the shape of the reference operator's RayCluster CR
(``python/ray/ray_operator/operator_utils.py`` cr -> autoscaler config
translation) without depending on Kubernetes: the CR is a plain dict
(what a K8s watch would deliver) parsed into typed dataclasses.

TPU extension (no reference analog): ``WorkerGroupSpec.accelerator`` +
``topology`` declare that each replica of the group is one TPU slice;
``num_hosts`` is derived from the topology so the operator gang-creates
that many pods per replica.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class HeadGroupSpec:
    resources: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"CPU": 1.0})
    pod_template: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class WorkerGroupSpec:
    name: str
    replicas: int = 1
    min_replicas: int = 0
    max_replicas: int = 10
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: TPU slice per replica, e.g. accelerator="v5e", topology="4x4".
    accelerator: str = ""
    topology: str = ""
    pod_template: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def num_hosts(self) -> int:
        """Pods per replica: 1 for CPU groups, the slice host count for
        TPU groups (a replica is an ICI domain, scaled atomically)."""
        if not self.accelerator:
            return 1
        from ray_tpu.parallel.topology import (parse_accelerator_type,
                                               parse_topology)
        if self.topology:
            return parse_topology(self.accelerator, self.topology).num_hosts
        return parse_accelerator_type(self.accelerator).num_hosts

    def clamped_replicas(self) -> int:
        return max(self.min_replicas, min(self.replicas, self.max_replicas))


@dataclasses.dataclass
class RayClusterSpec:
    name: str
    head: HeadGroupSpec = dataclasses.field(default_factory=HeadGroupSpec)
    worker_groups: List[WorkerGroupSpec] = dataclasses.field(
        default_factory=list)

    @classmethod
    def from_dict(cls, cr: Dict[str, Any]) -> "RayClusterSpec":
        """Parse a RayCluster CR body (``metadata`` + ``spec`` sections,
        the shape a K8s watch event carries)."""
        meta = cr.get("metadata", {})
        spec = cr.get("spec", {})
        head = HeadGroupSpec(
            resources=dict(spec.get("headGroupSpec", {}).get(
                "resources", {"CPU": 1.0})),
            pod_template=spec.get("headGroupSpec", {}).get("template", {}))
        groups = []
        for g in spec.get("workerGroupSpecs", []):
            groups.append(WorkerGroupSpec(
                name=g["groupName"],
                replicas=int(g.get("replicas", 1)),
                min_replicas=int(g.get("minReplicas", 0)),
                max_replicas=int(g.get("maxReplicas", 10)),
                resources=dict(g.get("resources", {})),
                accelerator=g.get("accelerator", ""),
                topology=g.get("topology", ""),
                pod_template=g.get("template", {})))
        return cls(name=meta.get("name", "raycluster"), head=head,
                   worker_groups=groups)

    def group(self, name: str) -> Optional[WorkerGroupSpec]:
        for g in self.worker_groups:
            if g.name == name:
                return g
        return None
