"""Job submission: run driver scripts on the cluster with status + logs.

Role-equivalent of the reference's job submission stack (reference
``dashboard/modules/job/job_manager.py:376 JobManager``, ``:128
JobSupervisor``, ``:520 submit_job``; REST/SDK/CLI under
``dashboard/modules/job/``).
"""

from ray_tpu.job.manager import (JobInfo, JobStatus, get_job_info,
                                 get_job_logs, get_job_status, list_jobs,
                                 stop_job, submit_job, wait_job)

__all__ = [
    "JobStatus", "JobInfo", "submit_job", "get_job_status", "get_job_info",
    "get_job_logs", "list_jobs", "stop_job", "wait_job",
]
