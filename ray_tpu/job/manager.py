"""JobManager + JobSupervisor actors and the client API.

Reference mapping:
* ``JobManager`` (reference ``dashboard/modules/job/job_manager.py:376``)
  -> a detached named actor owning the job table (persisted in GCS KV so
  it survives the manager actor itself) and spawning supervisors.
* ``JobSupervisor`` (reference ``:128``) -> a detached actor per job that
  runs the entrypoint as a child process inside the job's runtime env,
  streams its output to a log buffer, and reports terminal status.
* ``submit_job`` / status / logs / stop / list (reference ``:520`` and
  the REST routes) -> module-level client functions + dashboard routes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

JOB_MANAGER_NAME = "RAYTPU_JOB_MANAGER"
_KV_PREFIX = "job:"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    metadata: Dict[str, str] = field(default_factory=dict)
    start_time: float = 0.0
    end_time: float = 0.0


class JobSupervisor:
    """Detached actor running one job's entrypoint as a subprocess.

    The child gets RAYTPU_ADDRESS so `ray_tpu.init(address="auto")` in the
    script attaches to this cluster (reference: the supervisor exports
    RAY_ADDRESS, job_manager.py:128).
    """

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Dict[str, Any], gcs_address: str):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.gcs_address = gcs_address
        self._logs: List[str] = []
        self._proc: Optional[subprocess.Popen] = None
        self._status = JobStatus.PENDING
        self._message = ""
        self._stop_requested = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"job-{job_id}")
        self._thread.start()

    def _setup_env(self) -> tuple:
        from ray_tpu import runtime_env as re_mod
        from ray_tpu._private import worker_context

        env = dict(os.environ)
        env["RAYTPU_ADDRESS"] = self.gcs_address
        env["RAYTPU_JOB_ID"] = self.job_id
        cwd = None
        if self.runtime_env:
            cw = worker_context.core_worker()
            cache = os.path.join(
                os.environ.get("RAYTPU_SESSION_DIR", "/tmp/ray_tpu"),
                "runtime_envs")
            ctx = re_mod.materialize(self.runtime_env, cw.kv_get, cache)
            cwd = ctx.apply(env)
            if ctx.command_prefix:
                # container plugin: wrap the shell entrypoint, forwarding
                # the cluster handshake + runtime-env vars INTO the
                # container (the engine child doesn't inherit our env)
                import shlex

                fwd = dict(ctx.env_vars)
                fwd["RAYTPU_ADDRESS"] = env["RAYTPU_ADDRESS"]
                fwd["RAYTPU_JOB_ID"] = env["RAYTPU_JOB_ID"]
                prefix = list(ctx.command_prefix)
                image = prefix.pop()
                for k, v in fwd.items():
                    prefix += ["-e", f"{k}={v}"]
                prefix.append(image)
                self.entrypoint = " ".join(
                    shlex.quote(p) for p in prefix
                ) + " /bin/sh -c " + shlex.quote(self.entrypoint)
        return env, cwd

    def _run(self):
        try:
            env, cwd = self._setup_env()
        except Exception as e:  # noqa: BLE001 - env setup failed
            self._status = JobStatus.FAILED
            self._message = f"runtime_env setup failed: {e}"
            self._logs.append(self._message)
            return
        try:
            self._proc = subprocess.Popen(
                self.entrypoint, shell=True, env=env, cwd=cwd,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, bufsize=1)
        except Exception as e:  # noqa: BLE001
            self._status = JobStatus.FAILED
            self._message = f"failed to start entrypoint: {e}"
            return
        self._status = JobStatus.RUNNING
        for line in self._proc.stdout:
            self._logs.append(line.rstrip("\n"))
            if len(self._logs) > 100_000:
                del self._logs[:50_000]
        rc = self._proc.wait()
        if self._stop_requested:
            self._status = JobStatus.STOPPED
            self._message = "stopped by user"
        elif rc == 0:
            self._status = JobStatus.SUCCEEDED
        else:
            self._status = JobStatus.FAILED
            self._message = f"entrypoint exited with code {rc}"

    def status(self) -> Dict[str, str]:
        return {"status": self._status, "message": self._message}

    def logs(self, tail: int = -1) -> str:
        lines = self._logs if tail < 0 else self._logs[-tail:]
        return "\n".join(lines)

    def stop(self) -> bool:
        self._stop_requested = True
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.terminate()
                time.sleep(1.0)
                if self._proc.poll() is None:
                    self._proc.kill()
            except Exception:  # noqa: BLE001
                pass
        return True

    def ping(self) -> bool:
        return True


class JobManager:
    """Detached actor owning the job table (GCS-KV-persisted)."""

    def __init__(self):
        import ray_tpu  # noqa: F401 - actor runs inside an initialized worker
        from ray_tpu._private import worker_context

        self._cw = worker_context.core_worker()
        self._supervisors: Dict[str, Any] = {}
        self._gcs_address = os.environ.get("RAYTPU_GCS_ADDRESS", "")

    # -- persistence -------------------------------------------------------

    def _save(self, info: JobInfo):
        self._cw.kv_put(_KV_PREFIX + info.job_id,
                        json.dumps(asdict(info)).encode())

    def _load(self, job_id: str) -> Optional[JobInfo]:
        raw = self._cw.kv_get(_KV_PREFIX + job_id)
        return JobInfo(**json.loads(raw)) if raw else None

    # -- API ---------------------------------------------------------------

    def submit(self, entrypoint: str, runtime_env: Optional[dict] = None,
               metadata: Optional[dict] = None,
               job_id: Optional[str] = None) -> str:
        import ray_tpu
        from ray_tpu import runtime_env as re_mod

        job_id = job_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        if self._load(job_id) is not None:
            raise ValueError(f"job {job_id} already exists")
        packed = re_mod.pack(re_mod.validate(runtime_env), self._cw.kv_put)
        info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                       runtime_env=packed, metadata=metadata or {},
                       start_time=time.time())
        self._save(info)
        sup = ray_tpu.remote(num_cpus=0, lifetime="detached",
                             name=f"RAYTPU_JOB_SUP:{job_id}")(
            JobSupervisor).remote(job_id, entrypoint, packed,
                                  self._gcs_address)
        self._supervisors[job_id] = sup
        return job_id

    def _refresh(self, info: JobInfo) -> JobInfo:
        if info.status in JobStatus.TERMINAL:
            return info
        import ray_tpu

        sup = self._supervisors.get(info.job_id)
        if sup is None:
            info.status = JobStatus.FAILED
            info.message = "supervisor lost (job manager restarted)"
        else:
            try:
                st = ray_tpu.get(sup.status.remote(), timeout=30)
                info.status = st["status"]
                info.message = st["message"]
            except Exception as e:  # noqa: BLE001 - supervisor died
                info.status = JobStatus.FAILED
                info.message = f"supervisor died: {e}"
        if info.status in JobStatus.TERMINAL and not info.end_time:
            info.end_time = time.time()
        self._save(info)
        return info

    def status(self, job_id: str) -> dict:
        info = self._load(job_id)
        if info is None:
            raise ValueError(f"no such job {job_id}")
        return asdict(self._refresh(info))

    def logs(self, job_id: str, tail: int = -1) -> str:
        import ray_tpu

        sup = self._supervisors.get(job_id)
        if sup is None:
            return ""
        try:
            return ray_tpu.get(sup.logs.remote(tail), timeout=30)
        except Exception:  # noqa: BLE001
            return ""

    def stop(self, job_id: str) -> bool:
        import ray_tpu

        sup = self._supervisors.get(job_id)
        if sup is None:
            return False
        ok = ray_tpu.get(sup.stop.remote(), timeout=30)
        info = self._load(job_id)
        if info is not None:
            info.status = JobStatus.STOPPED
            info.message = "stopped by user"
            info.end_time = time.time()
            self._save(info)
        return ok

    def list(self) -> List[dict]:
        out = []
        for key in self._cw.kv_keys(_KV_PREFIX):
            raw = self._cw.kv_get(key)
            if raw:
                out.append(asdict(self._refresh(JobInfo(**json.loads(raw)))))
        return sorted(out, key=lambda j: j["start_time"])

    def ping(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Client API (reference: JobSubmissionClient SDK)
# ---------------------------------------------------------------------------

def _manager():
    import ray_tpu

    ray_tpu._auto_init()
    try:
        return ray_tpu.get_actor(JOB_MANAGER_NAME)
    except ValueError:
        return ray_tpu.remote(num_cpus=0, lifetime="detached",
                              name=JOB_MANAGER_NAME)(JobManager).remote()


def submit_job(entrypoint: str, *, runtime_env: Optional[dict] = None,
               metadata: Optional[dict] = None,
               job_id: Optional[str] = None) -> str:
    import ray_tpu

    m = _manager()
    return ray_tpu.get(m.submit.remote(entrypoint, runtime_env, metadata,
                                       job_id), timeout=120)


def get_job_info(job_id: str) -> JobInfo:
    import ray_tpu

    return JobInfo(**ray_tpu.get(_manager().status.remote(job_id),
                                 timeout=60))


def get_job_status(job_id: str) -> str:
    return get_job_info(job_id).status


def get_job_logs(job_id: str, tail: int = -1) -> str:
    import ray_tpu

    return ray_tpu.get(_manager().logs.remote(job_id, tail), timeout=60)


def list_jobs() -> List[JobInfo]:
    import ray_tpu

    return [JobInfo(**j) for j in
            ray_tpu.get(_manager().list.remote(), timeout=60)]


def stop_job(job_id: str) -> bool:
    import ray_tpu

    return ray_tpu.get(_manager().stop.remote(job_id), timeout=60)


def wait_job(job_id: str, timeout: float = 300.0,
             poll_s: float = 0.5) -> JobInfo:
    """Block until the job reaches a terminal state."""
    deadline = time.monotonic() + timeout
    while True:
        info = get_job_info(job_id)
        if info.status in JobStatus.TERMINAL:
            return info
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"job {job_id} still {info.status} after {timeout}s")
        time.sleep(poll_s)
