"""Runtime environments: per-job / per-actor / per-task execution context
(working_dir, env_vars, py_modules, pip).

Role-equivalent of the reference's runtime-env system (reference
``python/ray/_private/runtime_env/plugin.py:24 RuntimeEnvPlugin``,
``:116 RuntimeEnvPluginManager``; packaging
``_private/runtime_env/packaging.py``).  Collapsed TPU-build design:

* the client **packs** local directories into content-addressed zip
  archives stored in GCS KV (``gcs://runtimeenv/<sha1>`` URIs — the role
  of the reference's GCS-backed package URIs);
* the **node manager** materializes URIs into a per-node cache directory
  and starts the worker with the right cwd / PYTHONPATH / env vars (the
  role of the reference's per-node dashboard agent installing envs for
  the raylet, ``dashboard/modules/runtime_env/``);
* plugins are entries in ``PLUGINS`` keyed by the runtime-env field they
  own — third parties can register their own.
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile
from typing import Any, Callable, Dict, List, Optional

_URI_PREFIX = "gcs://runtimeenv/"
_KV_PREFIX = "runtimeenv:"
MAX_PACKAGE_BYTES = 100 * 1024 * 1024  # reference caps GCS packages at 100MB

KNOWN_FIELDS = ("working_dir", "env_vars", "py_modules", "pip",
                "conda", "container")


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Normalize + validate a runtime env dict (client side)."""
    if not runtime_env:
        return {}
    out = dict(runtime_env)
    for k in out:
        if k not in KNOWN_FIELDS:
            raise ValueError(
                f"unknown runtime_env field {k!r}; known: {KNOWN_FIELDS}")
    ev = out.get("env_vars")
    if ev is not None and not all(
            isinstance(k, str) and isinstance(v, str) for k, v in ev.items()):
        raise ValueError("env_vars must be Dict[str, str]")
    return out


# ---------------------------------------------------------------------------
# Packing (client side)
# ---------------------------------------------------------------------------

def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in
                       ("__pycache__", ".git", ".venv", "node_modules")]
            for f in files:
                if f.endswith(".pyc"):
                    continue
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, path))
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"packed dir {path} is {len(data)} bytes "
            f"(cap {MAX_PACKAGE_BYTES}); trim it or use py_modules")
    return data


def upload_dir(kv_put: Callable[[str, bytes], Any], path: str) -> str:
    """Zip ``path`` into GCS KV; returns its content-addressed URI."""
    if not os.path.isdir(path):
        raise ValueError(f"not a directory: {path}")
    data = _zip_dir(path)
    digest = hashlib.sha1(data).hexdigest()
    kv_put(_KV_PREFIX + digest, data)
    return _URI_PREFIX + digest


def pack(runtime_env: Dict[str, Any],
         kv_put: Callable[[str, bytes], Any]) -> Dict[str, Any]:
    """Resolve local paths in a validated runtime env to uploaded URIs —
    after this the dict is location-independent and can ride task/actor
    specs."""
    out = dict(runtime_env)
    wd = out.get("working_dir")
    if wd and not wd.startswith(_URI_PREFIX):
        out["working_dir"] = upload_dir(kv_put, wd)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [
            m if m.startswith(_URI_PREFIX) else upload_dir(kv_put, m)
            for m in mods]
    return out


def env_hash(runtime_env: Dict[str, Any]) -> str:
    """Stable identity of a packed env (worker-pool cache key; reference:
    runtime-env hash in the worker pool, worker_pool.h:156)."""
    import json

    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()


# ---------------------------------------------------------------------------
# Materialization (node side)
# ---------------------------------------------------------------------------

class RuntimeEnvContext:
    """What a materialized env does to a worker process."""

    def __init__(self):
        self.env_vars: Dict[str, str] = {}
        self.cwd: Optional[str] = None
        self.py_paths: List[str] = []
        #: argv prefix wrapping the launched command (container plugin:
        #: ["docker", "run", ..., image]); empty = run directly.
        self.command_prefix: List[str] = []

    def apply(self, env: Dict[str, str]) -> Optional[str]:
        """Mutate a subprocess env dict; returns the cwd override."""
        env.update(self.env_vars)
        if self.py_paths:
            env["PYTHONPATH"] = os.pathsep.join(
                self.py_paths + [env.get("PYTHONPATH", "")]).rstrip(
                    os.pathsep)
        return self.cwd


def _fetch_uri(kv_get: Callable[[str], Optional[bytes]], uri: str,
               cache_dir: str) -> str:
    """Materialize a gcs:// zip URI into cache_dir; returns the dir."""
    digest = uri[len(_URI_PREFIX):]
    dest = os.path.join(cache_dir, digest)
    if os.path.isdir(dest):
        return dest  # content-addressed: immutable once extracted
    data = kv_get(_KV_PREFIX + digest)
    if data is None:
        raise RuntimeError(f"runtime env package {uri} not found in GCS")
    tmp = dest + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        z.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:  # raced another materialization
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


# Plugin registry: field name -> setup(value, ctx, kv_get, cache_dir).
# (Reference: RuntimeEnvPlugin.create/modify_context, plugin.py:24.)

def _setup_env_vars(value, ctx, kv_get, cache_dir):
    ctx.env_vars.update(value)


def _setup_working_dir(value, ctx, kv_get, cache_dir):
    path = _fetch_uri(kv_get, value, cache_dir)
    ctx.cwd = path
    ctx.py_paths.insert(0, path)


def _setup_py_modules(value, ctx, kv_get, cache_dir):
    for uri in value:
        ctx.py_paths.append(_fetch_uri(kv_get, uri, cache_dir))


def _setup_pip(value, ctx, kv_get, cache_dir):
    """pip installs need an index; this build targets hermetic clusters,
    so we create a venv only when the packages are already importable is
    NOT checkable cheaply — instead fail fast with a clear error unless
    the operator pointed RAYTPU_PIP_INDEX at a reachable index/wheelhouse."""
    import subprocess
    import sys

    args = list(value) if isinstance(value, (list, tuple)) else [value]
    key = hashlib.sha1(repr(sorted(args)).encode()).hexdigest()
    venv = os.path.join(cache_dir, f"pip-{key}")
    site = os.path.join(venv, "lib", f"python{sys.version_info.major}."
                        f"{sys.version_info.minor}", "site-packages")
    if not os.path.isdir(venv):
        import venv as venv_mod

        venv_mod.EnvBuilder(with_pip=True,
                            system_site_packages=True).create(venv)
        cmd = [os.path.join(venv, "bin", "python"), "-m", "pip", "install",
               "--quiet"]
        index = os.environ.get("RAYTPU_PIP_INDEX", "")
        if index:
            cmd += ["--index-url", index]
        r = subprocess.run(cmd + args, capture_output=True, text=True,
                           timeout=600)
        if r.returncode != 0:
            import shutil

            shutil.rmtree(venv, ignore_errors=True)
            raise RuntimeError(
                f"runtime_env pip install failed: {r.stderr[-500:]}")
    ctx.py_paths.append(site)


def _conda_base() -> str:
    import shutil
    import subprocess

    exe = shutil.which("conda") or shutil.which("micromamba") or \
        shutil.which("mamba")
    if not exe:
        raise RuntimeError(
            "runtime_env 'conda' needs a conda/mamba binary on PATH "
            "(none found on this host)")
    r = subprocess.run([exe, "info", "--base"], capture_output=True,
                       text=True, timeout=60)
    if r.returncode != 0:
        raise RuntimeError(f"conda info --base failed: {r.stderr[-300:]}")
    return r.stdout.strip().splitlines()[-1]


def _setup_conda(value, ctx, kv_get, cache_dir):
    """Activate a conda env for the launched process (reference:
    _private/runtime_env/conda.py — named env activation or creation
    from an environment-yaml dict, cached by content hash).  Activation
    is the environment-variable effect of ``conda activate``: env bin/
    on PATH + CONDA_PREFIX/CONDA_DEFAULT_ENV set — no shell involved."""
    import shutil
    import subprocess

    base = _conda_base()
    if isinstance(value, str):
        prefix = value if os.sep in value else os.path.join(
            base, "envs", value)
        if not os.path.isdir(prefix):
            raise RuntimeError(f"conda env {value!r} not found at "
                               f"{prefix}")
        name = value
    elif isinstance(value, dict):
        key = hashlib.sha1(repr(sorted(value.items())).encode()
                           ).hexdigest()[:12]
        name = f"raytpu-{key}"
        prefix = os.path.join(base, "envs", name)
        if not os.path.isdir(prefix):
            spec = os.path.join(cache_dir, f"conda-{key}.yml")
            import json as _json

            with open(spec, "w") as f:
                # conda yaml is a JSON subset for the fields we emit
                _json.dump(dict(value, name=name), f)
            exe = shutil.which("conda") or shutil.which("mamba") or \
                shutil.which("micromamba")
            # create into a temp prefix, rename on success: a killed or
            # failed create must never leave a half-built env that later
            # materializations would silently activate
            tmp_prefix = prefix + ".tmp"
            shutil.rmtree(tmp_prefix, ignore_errors=True)
            try:
                r = subprocess.run(
                    [exe, "env", "create", "-f", spec, "-p", tmp_prefix],
                    capture_output=True, text=True, timeout=1800)
            except subprocess.TimeoutExpired:
                shutil.rmtree(tmp_prefix, ignore_errors=True)
                raise RuntimeError("conda env create timed out")
            if r.returncode != 0:
                shutil.rmtree(tmp_prefix, ignore_errors=True)
                raise RuntimeError(
                    f"conda env create failed: {r.stderr[-500:]}")
            os.rename(tmp_prefix, prefix)
    else:
        raise RuntimeError("runtime_env 'conda' must be an env name or "
                           "an environment dict")
    ctx.env_vars["CONDA_PREFIX"] = prefix
    ctx.env_vars["CONDA_DEFAULT_ENV"] = name
    ctx.env_vars["PATH"] = (os.path.join(prefix, "bin") + os.pathsep
                            + os.environ.get("PATH", ""))


def _setup_container(value, ctx, kv_get, cache_dir):
    """Run the launched process inside a container image (reference:
    _private/runtime_env/container.py — worker_process_setup via
    podman).  Scope: JOB entrypoints (the job supervisor applies
    ``command_prefix``); this runtime's forked task workers stay on the
    host, documented divergence from the reference's containerized
    workers."""
    import shutil

    if not isinstance(value, dict) or "image" not in value:
        raise RuntimeError("runtime_env 'container' needs "
                           "{'image': ..., 'run_options': [...]}")
    engine = shutil.which("podman") or shutil.which("docker")
    if not engine:
        raise RuntimeError("runtime_env 'container' needs podman or "
                           "docker on PATH (none found)")
    ctx.command_prefix = [engine, "run", "--rm", "--network=host",
                          *value.get("run_options", []),
                          value["image"]]


PLUGINS: Dict[str, Callable] = {
    "env_vars": _setup_env_vars,
    "working_dir": _setup_working_dir,
    "py_modules": _setup_py_modules,
    "pip": _setup_pip,
    "conda": _setup_conda,
    "container": _setup_container,
}


def register_plugin(field: str, setup: Callable) -> None:
    PLUGINS[field] = setup


def materialize(runtime_env: Dict[str, Any],
                kv_get: Callable[[str], Optional[bytes]],
                cache_dir: str) -> RuntimeEnvContext:
    """Run every plugin for a packed env; returns the worker context.
    (Reference: RuntimeEnvPluginManager driving plugin setup,
    plugin.py:116.)"""
    ctx = RuntimeEnvContext()
    os.makedirs(cache_dir, exist_ok=True)
    for field, value in runtime_env.items():
        plugin = PLUGINS.get(field)
        if plugin is None:
            raise RuntimeError(f"no runtime_env plugin for field {field!r}")
        plugin(value, ctx, kv_get, cache_dir)
    return ctx
