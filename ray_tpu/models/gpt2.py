"""GPT-2 family, TPU-first.

The flagship model for the north-star benchmark (BASELINE.json: "GPT-2-125M
language modeling, pjit FSDP across pod").  The reference has no model zoo
of its own — Ray Train wraps user torch modules (reference
python/ray/train/torch/train_loop_utils.py:28 prepare_model); here the
framework ships the model because the TPU path *is* the framework's value.

Design choices (all TPU-motivated, none ported):
  * pure functional init/apply over a param pytree — jit/grad/shard friendly;
  * layers stacked on a leading axis and iterated with `lax.scan` — one
    layer gets traced/compiled once regardless of depth;
  * every param dim carries a logical axis name; DP/FSDP/TP/SP are rule
    tables (ray_tpu/parallel/sharding.py), not model edits;
  * compute in bfloat16 on the MXU, params + optimizer state in float32;
  * per-layer `jax.checkpoint` (remat) so activation memory is O(sqrt)
    and HBM goes to batch instead;
  * attention dispatches to the pallas flash kernel on TPU
    (ray_tpu/ops/flash_attention.py), plain XLA softmax elsewhere.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.sharding import DEFAULT_RULES, with_logical_constraint

#: The three lm-head + cross-entropy implementations (GPT2Config.ce_impl):
#: "dense" materializes f32 (B,T,V) logits; "streaming_xla" is the
#: lax.scan vocab-tile path (ops/vocab_ce.py); "pallas" is the fused
#: MXU-streamed kernel (ops/fused_ce.py) — no (B,T,V) buffer in either
#: pass.  See PERF_NOTES round 6 for when each wins.
CE_IMPLS = ("dense", "streaming_xla", "pallas")
FLASH_RESIDENT_MODES = ("auto", "on", "off")


def ce_config_problems(ce_impl: str, flash_resident: str, *,
                       loss_chunks: int = 1,
                       seq_parallel: bool = False) -> list:
    """Validation shared by GPT2Config/LlamaConfig: returns a list of
    human-readable problems with the CE/attention knob combination (empty
    when valid).  Callers join the list into ONE coherent ValueError so
    an invalid config reports every conflict at once instead of the
    first scattered check to trip."""
    problems = []
    if ce_impl not in CE_IMPLS:
        problems.append(f"ce_impl must be one of {CE_IMPLS} "
                        f"(got {ce_impl!r})")
    else:
        if ce_impl != "dense" and loss_chunks > 1:
            problems.append(
                f"loss_chunks={loss_chunks} requires ce_impl='dense' "
                f"(both bound the logits footprint; pick one)")
        if ce_impl != "dense" and seq_parallel:
            problems.append(
                f"ce_impl={ce_impl!r} needs an unsharded seq axis (the "
                f"(B,T)->(B*T) flatten would reshard under seq "
                f"parallelism)")
    if flash_resident not in FLASH_RESIDENT_MODES:
        problems.append(f"flash_resident must be one of "
                        f"{FLASH_RESIDENT_MODES} (got {flash_resident!r})")
    return problems


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_seq: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16        # activation/compute dtype (MXU-native)
    param_dtype: Any = jnp.float32   # master weights
    remat: bool = True
    #: "full" = recompute everything (min memory); "dots" = save every
    #: matmul output (incl. the O(T^2) attention scores — usually a bad
    #: trade); "dots_nb" = save matmul outputs with no batch dims, i.e.
    #: the weight matmuls but NOT attention scores — recompute the
    #: HBM-heavy softmax, keep the MXU work.
    remat_policy: str = "full"
    use_flash: Optional[bool] = None  # None = auto (flash on TPU)
    #: Split the (B,T,V) logits/loss computation into this many sequence
    #: chunks so the float32 logits tensor never fully materializes (its
    #: HBM footprint, B*T*V*4 bytes, otherwise dominates and caps batch).
    #: Each chunk is rematerialized in the backward pass.  Leave at 1 when
    #: the sequence axis is mesh-sharded (reshape would break the layout).
    loss_chunks: int = 1
    #: lax.scan unroll factor for the layer stack: >1 lets XLA overlap one
    #: layer's weight loads with the previous layer's compute.
    scan_unroll: int = 1
    #: lm-head + cross-entropy implementation — see CE_IMPLS above.  The
    #: non-dense impls need an unsharded seq axis (the (B,T)->(B*T)
    #: flatten would reshard) and are mutually exclusive with
    #: loss_chunks>1; validated coherently in __post_init__.
    ce_impl: str = "dense"
    #: DEPRECATED alias for ce_impl="streaming_xla" (the pre-round-6
    #: knob); normalized into ce_impl by __post_init__.
    use_streaming_ce: bool = False
    vocab_tile: int = 8192
    #: pallas fused-CE tile sizes (ce_impl="pallas"): block_n rows of
    #: flattened (B*T, D) hidden per vocab stream, block_v vocab columns
    #: per MXU tile.  Defaults sized for GPT-2 D=768 on v5e VMEM
    #: (ops/fused_ce.py).
    ce_block_n: int = 256
    ce_block_v: int = 1024
    #: resident-kv flash attention dispatch: "auto" = the measured
    #: policy (ops/flash_attention._resident_plan), "on"/"off" force it.
    #: RAYTPU_FLASH_RESIDENT=1/0 in the env overrides the config — the
    #: process-wide A/B workflow keeps working.
    flash_resident: str = "auto"
    seq_parallel: bool = False  # context parallelism over the "seq" axis
    #: context-parallel algorithm: "ring" (kv blocks rotate by ppermute,
    #: O(T/n) memory) or "ulysses" (head-scatter/seq-gather all-to-all —
    #: cheaper collectives when heads >> seq shards)
    sp_mode: str = "ring"
    #: >0 replaces every block's dense MLP with a mixture-of-experts FF
    #: (ray_tpu.models.moe) routed top-k over the `expert` mesh axis.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    #: weight of the Switch load-balancing aux loss added by gpt2_loss
    moe_aux_weight: float = 0.01
    # pad vocab to a multiple of 128 so the logits matmul tiles the MXU
    # cleanly and the vocab dim shards evenly under tensor parallelism
    vocab_pad_to: int = 128

    def __post_init__(self):
        if self.use_streaming_ce and self.ce_impl == "dense":
            object.__setattr__(self, "ce_impl", "streaming_xla")
        problems = ce_config_problems(
            self.ce_impl, self.flash_resident,
            loss_chunks=self.loss_chunks, seq_parallel=self.seq_parallel)
        if self.use_streaming_ce and self.ce_impl == "pallas":
            problems.append(
                "use_streaming_ce is a deprecated alias for "
                "ce_impl='streaming_xla' and conflicts with "
                "ce_impl='pallas'")
        if problems:
            raise ValueError("invalid GPT2Config: " + "; ".join(problems))

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def padded_vocab(self) -> int:
        p = self.vocab_pad_to
        return (self.vocab_size + p - 1) // p * p


_PRESETS = {
    # name: (n_layer, n_head, d_model)
    "nano": (2, 2, 64),          # test-sized
    "tiny": (4, 4, 128),
    "gpt2": (12, 12, 768),       # 124M — the north-star config
    "gpt2-medium": (24, 16, 1024),
    "gpt2-large": (36, 20, 1280),
    "gpt2-xl": (48, 25, 1600),
}


def gpt2_config(name: str = "gpt2", **overrides) -> GPT2Config:
    n_layer, n_head, d_model = _PRESETS[name]
    kw: Dict[str, Any] = dict(n_layer=n_layer, n_head=n_head,
                              d_model=d_model, d_ff=4 * d_model)
    if name in ("nano", "tiny"):
        kw.update(vocab_size=512, max_seq=128)
    kw.update(overrides)
    return GPT2Config(**kw)


def gpt2_param_count(cfg: GPT2Config) -> int:
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layer
    if cfg.n_experts:
        E = cfg.n_experts
        ff = d * E + E * (2 * d * f + d + f)  # gate + E experts
    else:
        ff = 2 * d * f + d + f
    per_layer = (4 * d * d + 4 * d) + ff + 4 * d  # attn+ff+2ln
    return cfg.vocab_size * d + cfg.max_seq * d + L * per_layer + 2 * d


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def gpt2_logical_axes(cfg: GPT2Config) -> Dict[str, Any]:
    """Pytree (matching gpt2_init's) of logical-axis tuples.

    Leading `None` on block leaves is the stacked-layer axis.  "embed" maps
    to fsdp (ZeRO-3), "heads"/"mlp"/"vocab" to tensor — see
    parallel/sharding.py DEFAULT_RULES.
    """
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "ln_f": {"scale": ("embed",), "bias": ("embed",)},
        "blocks": {
            "ln1": {"scale": (None, "embed"), "bias": (None, "embed")},
            "ln2": {"scale": (None, "embed"), "bias": (None, "embed")},
            "attn": {
                "qkv_w": (None, "embed", None, "heads", "head_dim"),
                "qkv_b": (None, None, "heads", "head_dim"),
                "o_w": (None, "heads", "head_dim", "embed"),
                "o_b": (None, "embed"),
            },
            **({"moe": {
                "gate": (None, "embed", None),
                "w1": (None, "expert", "embed", "mlp"),
                "b1": (None, "expert", "mlp"),
                "w2": (None, "expert", "mlp", "embed"),
                "b2": (None, "expert", "embed"),
            }} if cfg.n_experts else {"mlp": {
                "fc_w": (None, "embed", "mlp"),
                "fc_b": (None, "mlp"),
                "proj_w": (None, "mlp", "embed"),
                "proj_b": (None, "embed"),
            }}),
        },
    }


def gpt2_init(key, cfg: GPT2Config) -> Dict[str, Any]:
    """Initialize parameters (GPT-2 style: N(0, 0.02), residual projections
    scaled by 1/sqrt(2*n_layer))."""
    L, d, f, h, hd = (cfg.n_layer, cfg.d_model, cfg.d_ff, cfg.n_head,
                      cfg.head_dim)
    pd = cfg.param_dtype
    k = iter(jax.random.split(key, 8))
    std = 0.02
    res_std = std / math.sqrt(2 * L)

    def norm(kk, shape, s=std):
        return (jax.random.normal(kk, shape, dtype=jnp.float32) * s).astype(pd)

    return {
        "wte": norm(next(k), (cfg.padded_vocab, d)),
        "wpe": norm(next(k), (cfg.max_seq, d), s=0.01),
        "ln_f": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
        "blocks": {
            "ln1": {"scale": jnp.ones((L, d), pd),
                    "bias": jnp.zeros((L, d), pd)},
            "ln2": {"scale": jnp.ones((L, d), pd),
                    "bias": jnp.zeros((L, d), pd)},
            "attn": {
                "qkv_w": norm(next(k), (L, d, 3, h, hd)),
                "qkv_b": jnp.zeros((L, 3, h, hd), pd),
                "o_w": norm(next(k), (L, h, hd, d), s=res_std),
                "o_b": jnp.zeros((L, d), pd),
            },
            **({"moe": {
                "gate": norm(next(k), (L, d, cfg.n_experts)),
                "w1": norm(next(k), (L, cfg.n_experts, d, f)),
                "b1": jnp.zeros((L, cfg.n_experts, f), pd),
                "w2": norm(next(k), (L, cfg.n_experts, f, d),
                           s=res_std),
                "b2": jnp.zeros((L, cfg.n_experts, d), pd),
            }} if cfg.n_experts else {"mlp": {
                "fc_w": norm(next(k), (L, d, f)),
                "fc_b": jnp.zeros((L, f), pd),
                "proj_w": norm(next(k), (L, f, d), s=res_std),
                "proj_b": jnp.zeros((L, d), pd),
            }}),
        },
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layernorm(x, scale, bias, eps=1e-5):
    # LN in float32 for stability, cast back to compute dtype.
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _heads_axis_sharded(rules) -> bool:
    """True when the active mesh shards the "heads" logical axis (tensor
    parallelism), in which case the flattened qkv GEMM must be avoided:
    merging (3, h, hd) puts the sharded h behind the unsharded 3, a
    reshape GSPMD cannot represent, forcing a per-layer weight
    all-gather."""
    try:
        from ray_tpu.parallel.mesh import active_mesh
        mesh = active_mesh()
        if mesh is None:
            return False
        from ray_tpu.parallel.sharding import logical_to_mesh_axes
        ax = logical_to_mesh_axes(("heads",), rules)[0]
        if ax is None:
            return False
        size = 1
        for a in (ax if isinstance(ax, (tuple, list)) else (ax,)):
            size *= mesh.shape.get(a, 1)
        return size > 1
    except Exception:  # noqa: BLE001 - no mesh machinery available
        return False


def _attention(x, p, cfg: GPT2Config, rules):
    B, T, d = x.shape
    h, hd = cfg.n_head, cfg.head_dim
    if _heads_axis_sharded(rules):
        # Megatron-TP path: keep the 5-D einsum so the heads axis stays
        # column-sharded through the contraction.
        qkv = jnp.einsum("btd,dchk->btchk", x, p["qkv_w"].astype(cfg.dtype))
    else:
        # Flattened-matmul form: XLA lowers the 5-D einsum
        # btd,dchk->btchk through a slow transpose path on TPU (measured
        # 10x slower than the equivalent (d, 3*h*hd) matmul on v5e), so
        # collapse the output axes and let the MXU see one big GEMM.
        # The reshape is free: (3, h, hd) are contiguous trailing axes.
        w = p["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (x @ w).reshape(B, T, 3, h, hd)
    qkv = qkv + p["qkv_b"].astype(cfg.dtype)
    q, kk, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B,T,H,hd)
    q = with_logical_constraint(q, ("batch", "seq", "heads", "head_dim"),
                                rules)
    o = None
    if cfg.seq_parallel:
        o = _ring_attention_sharded(q, kk, v, rules, cfg.sp_mode)
    if o is None:
        from ray_tpu.ops.attention import causal_attention
        o = causal_attention(q, kk, v, use_flash=cfg.use_flash,
                             resident=cfg.flash_resident)
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "attn_out")
    wo = p["o_w"].astype(cfg.dtype).reshape(h * hd, d)
    out = o.reshape(B, T, h * hd) @ wo
    return out + p["o_b"].astype(cfg.dtype)


def _ring_attention_sharded(q, k, v, rules, sp_mode: str = "ring"):
    """Context parallelism: the model stays GSPMD-partitioned, but
    attention (the one op coupling all sequence positions) drops into an
    explicit shard_map running ring attention over the "seq" mesh axis.
    Returns None when no mesh is active (e.g. single-device eval)."""
    import jax
    from jax.sharding import PartitionSpec

    try:
        from ray_tpu.parallel.mesh import active_mesh
        mesh = active_mesh()
        if mesh is None or mesh.shape.get("seq", 1) == 1:
            return None
    except Exception:  # noqa: BLE001 - no mesh machinery available
        return None
    from ray_tpu.ops.ring_attention import (ring_attention,
                                            ulysses_attention)
    from ray_tpu.parallel.sharding import logical_to_mesh_axes

    spec = logical_to_mesh_axes(("batch", "seq", "heads", "head_dim"),
                                rules)
    import functools

    fn = ulysses_attention if sp_mode == "ulysses" else ring_attention
    return jax.shard_map(
        functools.partial(fn, causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)


def _mlp(x, p, cfg: GPT2Config, rules):
    h = jnp.einsum("btd,df->btf", x, p["fc_w"].astype(cfg.dtype))
    h = jax.nn.gelu(h + p["fc_b"].astype(cfg.dtype))
    h = with_logical_constraint(h, ("batch", "seq", "mlp"), rules)
    out = jnp.einsum("btf,fd->btd", h, p["proj_w"].astype(cfg.dtype))
    return out + p["proj_b"].astype(cfg.dtype)


def _moe_cfg(cfg: GPT2Config):
    from ray_tpu.models.moe import MoEConfig

    return MoEConfig(d_model=cfg.d_model, d_ff=cfg.d_ff,
                     n_experts=cfg.n_experts, top_k=cfg.moe_top_k,
                     capacity_factor=cfg.moe_capacity_factor,
                     dtype=cfg.dtype, param_dtype=cfg.param_dtype)


def _block(x, layer_params, cfg: GPT2Config, rules):
    """Returns (x, moe_aux_loss) — aux is 0.0 for dense blocks."""
    p = layer_params
    x = x + _attention(
        _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"]), p["attn"], cfg,
        rules)
    xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
    if cfg.n_experts:
        from ray_tpu.models.moe import moe_apply

        y, aux = moe_apply(p["moe"], xm, _moe_cfg(cfg), rules)
    else:
        y, aux = _mlp(xm, p["mlp"], cfg, rules), jnp.float32(0.0)
    x = x + y
    x = with_logical_constraint(x, ("batch", "seq", "embed"), rules)
    return x, aux


def _flash_active(cfg: GPT2Config, T: int) -> bool:
    """Whether attention will actually take the flash kernel at seq T —
    the precondition for mlp_only remat's memory claim (the un-rematted
    NON-flash path would save O(T^2) score tensors per layer: ~25 GiB at
    B=32/T=1024/12 layers).  Mirrors causal_attention's dispatch."""
    if cfg.use_flash is False or cfg.seq_parallel:
        return False
    if cfg.use_flash is True:
        return True
    from ray_tpu.ops.attention import flash_auto_dispatch

    return flash_auto_dispatch(T, cfg.head_dim)


def gpt2_hidden(params, tokens, cfg: GPT2Config,
                rules=DEFAULT_RULES, return_aux: bool = False):
    """tokens (B, T) int32 → post-ln_f hidden states (B, T, d_model).
    return_aux=True additionally returns the summed MoE load-balance
    loss (0.0 for dense configs)."""
    B, T = tokens.shape
    # Stage the embedding lookup so GSPMD never faces a combined
    # table-shard → activation-shard transition (it would fall back to
    # "involuntary full rematerialization", b/433785288): replicate the
    # casted table FIRST (one all-gather — the partitioner emits the
    # same all-gather for a sharded-table gather anyway), then the local
    # gather inherits the token sharding (batch, seq) directly.
    wte = with_logical_constraint(params["wte"].astype(cfg.dtype),
                                  (None, None), rules)
    x = wte[tokens]
    # wpe slice: shard over seq to match x (T, d) + (B, T, d) broadcast;
    # constraining to its param sharding (embed→fsdp) would force an
    # fsdp→seq reshard of the activation instead.
    pos = with_logical_constraint(params["wpe"].astype(cfg.dtype)[:T],
                                  ("seq", None), rules)
    x = x + pos
    x = with_logical_constraint(x, ("batch", "seq", "embed"), rules)

    if cfg.remat and cfg.remat_policy == "mlp_only" and cfg.n_experts:
        raise NotImplementedError(
            "remat_policy='mlp_only' is a dense-MLP recipe; MoE blocks "
            "use remat_policy='full' (or 'dots_nb')")
    if cfg.remat and cfg.remat_policy == "mlp_only" \
            and _flash_active(cfg, T):
        # Sublayer-granular remat: the attention half is NOT rematted —
        # the flash kernel's backward recomputes score tiles internally
        # from O(T) residuals (q,k,v,o,lse), so re-running the flash
        # forward in the remat pass would be pure waste (~5.7ms/layer on
        # v5e at B=32) — while the activation-heavy MLP half (4x d_ff
        # hidden) is fully rematted.  Net: full-remat memory profile for
        # the MLP, dots-level speed for attention.
        def attn_half(x, p):
            return x + _attention(
                _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"]),
                p["attn"], cfg, rules)

        @partial(jax.checkpoint,
                 policy=jax.checkpoint_policies.nothing_saveable)
        def mlp_half(x, p):
            return x + _mlp(
                _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"]),
                p["mlp"], cfg, rules)

        def scan_body(carry, layer_params):
            h = attn_half(carry, layer_params)
            h = mlp_half(h, layer_params)
            h = with_logical_constraint(h, ("batch", "seq", "embed"),
                                        rules)
            return h, None

        x, _ = lax.scan(scan_body, x, params["blocks"],
                        unroll=cfg.scan_unroll)
        out = _layernorm(x, params["ln_f"]["scale"],
                         params["ln_f"]["bias"])
        return (out, jnp.float32(0.0)) if return_aux else out

    block = partial(_block, cfg=cfg, rules=rules)
    if cfg.remat:
        policy = {
            "dots": jax.checkpoint_policies.dots_saveable,
            "dots_nb":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            # save only the attention outputs (B,T,H,hd bf16 — 64 MiB per
            # GPT-2 layer at B=32): the backward pass then skips the
            # ln1 + qkv-matmul + flash-forward recompute, the costliest
            # part of full remat, at ~1/6 the memory of saving all dots.
            "attn_out":
                jax.checkpoint_policies.save_only_these_names("attn_out"),
        }.get(cfg.remat_policy, jax.checkpoint_policies.nothing_saveable)
        block = jax.checkpoint(block, policy=policy)

    def scan_body(carry, layer_params):
        return block(carry, layer_params)

    x, auxes = lax.scan(scan_body, x, params["blocks"],
                        unroll=cfg.scan_unroll)
    out = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    return (out, jnp.sum(auxes)) if return_aux else out


def _tied_logits(hidden, wte, cfg: GPT2Config, rules):
    """Tied-embedding projection — the ONE place defining the contract:
    bf16 operands with float32 accumulation (the MXU runs at bf16 rate
    while the softmax/loss still sees float32 logits; a pure-f32 matmul
    would run at 1/3 MXU rate via multi-pass)."""
    logits = jnp.einsum("btd,vd->btv", hidden, wte.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return with_logical_constraint(logits, ("batch", "seq", "vocab"),
                                   rules)


def gpt2_forward(params, tokens, cfg: GPT2Config,
                 rules=DEFAULT_RULES) -> jnp.ndarray:
    """tokens (B, T) int32 → logits (B, T, padded_vocab) float32."""
    x = gpt2_hidden(params, tokens, cfg, rules)
    return _tied_logits(x, params["wte"], cfg, rules)


def nll_from_logits(logits, targets, vocab_size: int,
                    padded_vocab: int):
    """Per-token negative log likelihood with the padded-vocab tail masked.

    Gather-free formulation: ``nll = logsumexp(logits) - logits[target]``
    with the target pick as a masked reduction over an iota comparison.
    A ``take_along_axis`` gather along a TENSOR-SHARDED vocab axis makes
    the SPMD partitioner replicate the full (B,T,V) float32 logits; the
    where/iota form partitions cleanly (local reduce + cross-shard sum),
    and XLA fuses the comparison into the reduction so nothing V-sized
    materializes beyond the logits themselves."""
    vocab_iota = lax.broadcasted_iota(jnp.int32, logits.shape,
                                      logits.ndim - 1)
    if padded_vocab != vocab_size:
        logits = jnp.where(vocab_iota < vocab_size, logits,
                           jnp.asarray(-1e9, logits.dtype))
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.sum(
        jnp.where(vocab_iota == targets[..., None], logits, 0),
        axis=-1)
    return lse - target_logit


def _nll_from_logits(logits, targets, cfg):
    """Config-taking shim over nll_from_logits (gpt2-internal)."""
    return nll_from_logits(logits, targets, cfg.vocab_size,
                           cfg.padded_vocab)


def _chunked_ce(hidden, wte, targets, mask, cfg: GPT2Config):
    """Cross-entropy over sequence chunks: the float32 (B,T,V) logits never
    fully materialize (only (B,T/C,V) per chunk, rematerialized in bwd)."""
    B, T, d = hidden.shape
    C = cfg.loss_chunks
    if T % C:
        raise ValueError(f"loss_chunks={C} must divide T={T}")
    Tc = T // C
    hs = jnp.moveaxis(hidden.reshape(B, C, Tc, d), 1, 0)
    ts = jnp.moveaxis(targets.reshape(B, C, Tc), 1, 0)
    ms = jnp.moveaxis(mask.reshape(B, C, Tc), 1, 0)
    wte_c = wte.astype(cfg.dtype)

    @jax.checkpoint
    def chunk_sums(hc, tc, mc):
        logits = jnp.einsum("btd,vd->btv", hc, wte_c,
                            preferred_element_type=jnp.float32)
        nll = _nll_from_logits(logits, tc, cfg)
        return jnp.sum(nll * mc), jnp.sum(mc)

    def body(carry, xs):
        s, n = chunk_sums(*xs)
        return (carry[0] + s, carry[1] + n), None

    (total, count), _ = lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (hs, ts, ms))
    return total / jnp.maximum(count, 1.0)


def lm_head_nll(hidden, w_vocab_major, targets, cfg) -> jnp.ndarray:
    """Per-token nll via the non-dense CE impls, shared by gpt2 and
    llama.  hidden (B, T, D); w_vocab_major (V, D) — tied wte, or a
    transposed lm_head for untied models; targets (B, T) int32.  cfg is
    any config carrying ce_impl / vocab_size / vocab_tile / ce_block_n /
    ce_block_v / dtype / padded_vocab.  Returns (B, T) float32."""
    B, T = targets.shape
    h2 = hidden.reshape(B * T, -1)
    t1 = targets.reshape(-1).astype(jnp.int32)
    if cfg.ce_impl == "pallas":
        from ray_tpu.ops.fused_ce import fused_lm_ce

        nll = fused_lm_ce(h2, w_vocab_major, t1, cfg.vocab_size,
                          block_n=cfg.ce_block_n,
                          block_v=min(cfg.ce_block_v, cfg.padded_vocab),
                          compute_dtype=cfg.dtype)
    else:
        from ray_tpu.ops.vocab_ce import streaming_ce

        nll = streaming_ce(h2, w_vocab_major, t1, cfg.vocab_size,
                           min(cfg.vocab_tile, cfg.padded_vocab),
                           cfg.dtype)
    return nll.reshape(B, T)


def gpt2_loss(params, batch, cfg: GPT2Config,
              rules=DEFAULT_RULES) -> jnp.ndarray:
    """Next-token cross-entropy.  batch = {"tokens": (B, T+1) int32} or
    {"inputs": (B,T), "targets": (B,T)}; padded-vocab tail masked out."""
    if "tokens" in batch:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    mask = batch.get("mask")
    hidden, aux = gpt2_hidden(params, inputs, cfg, rules,
                              return_aux=True)
    aux_term = cfg.moe_aux_weight * aux if cfg.n_experts else 0.0
    if cfg.ce_impl != "dense":
        # valid combinations were enforced at config construction
        # (__post_init__) — one coherent error, not scattered checks here
        nll = lm_head_nll(hidden, params["wte"], targets, cfg)
        if mask is not None:
            m = mask.astype(jnp.float32)
            return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m),
                                                  1.0) + aux_term
        return jnp.mean(nll) + aux_term
    if cfg.loss_chunks > 1:
        if mask is None:
            mask = jnp.ones(targets.shape, jnp.float32)
        return _chunked_ce(hidden, params["wte"], targets,
                           mask.astype(jnp.float32), cfg) + aux_term
    logits = _tied_logits(hidden, params["wte"], cfg, rules)
    nll = _nll_from_logits(logits, targets, cfg)
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask),
                                                 1.0) + aux_term
    return jnp.mean(nll) + aux_term
