"""Autoregressive decoding with a KV cache for the GPT-2 family.

The training path (gpt2.py) recomputes full-sequence attention; serving
needs incremental decode: O(1) new compute per token against cached
keys/values.  TPU-first choices:

  * static shapes everywhere — the cache is allocated at max_seq and
    slots outside [start, pos] are masked, so ONE compiled step serves
    the whole generation (no shape-polymorphic recompile);
  * prompt ingestion is a SINGLE full-sequence forward (`prefill`) that
    reuses the training-path attention (flash kernel where enabled),
    writes K/V for every prompt position with one dynamic_update_slice
    per cache tensor, and computes logits only at each row's last real
    token — O(1) dispatches instead of the old O(T0) per-token scan;
  * positions are per-sequence vectors (decode_common cache contract),
    so LEFT-padded ragged prompts decode correctly in one batch and a
    serve slot pool can host rows at different depths;
  * the per-token step is a `lax.scan` over the stacked layer params
    with the cache in the carry (same scan-stacked layout as training —
    one layer traced once).

No reference analog (the reference wraps user torch modules); this is
the piece that makes ray_tpu.serve a real LM server.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.decode_common import (generate_with, scan_prefill,
                                          slot_mask)
from ray_tpu.models.gpt2 import GPT2Config, _layernorm

__all__ = ["init_cache", "prefill", "decode_step", "generate"]


def init_cache(cfg: GPT2Config, batch: int) -> Dict[str, jnp.ndarray]:
    """Preallocated (L, B, S, H, hd) key/value cache + per-sequence
    position vectors (decode_common cache contract)."""
    if cfg.n_experts:
        raise NotImplementedError(
            "KV-cache decoding currently supports dense GPT-2 configs "
            "only (n_experts=0); MoE decode needs per-step routing")
    shape = (cfg.n_layer, batch, cfg.max_seq, cfg.n_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((batch,), jnp.int32),
            "start": jnp.zeros((batch,), jnp.int32)}


def prefill(params, tokens: jnp.ndarray, cfg: GPT2Config, *,
            lengths: Optional[jnp.ndarray] = None
            ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Single-dispatch prompt ingestion: tokens (B, T0) int32 →
    (last_logits (B, padded_vocab) float32, primed cache).

    Runs ONE full-sequence forward (training-path attention; flash
    kernel under the same dispatch rules) and writes K/V for all T0
    positions with one dynamic_update_slice per cache tensor.  Ragged
    batches pass `lengths` (B,): rows are LEFT-padded, so row b's real
    tokens sit at columns [T0 - lengths[b], T0) and the last real token
    is column T0-1 for every row — logits come from that one column,
    never the full (B, T0, V) tensor."""
    from ray_tpu.ops.attention import prefill_attention

    B, T0 = tokens.shape
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    cache = init_cache(cfg, B)
    if lengths is None:
        start = jnp.zeros((B,), jnp.int32)
        pos_ids = jnp.broadcast_to(jnp.arange(T0), (B, T0))
    else:
        start = (T0 - jnp.asarray(lengths, jnp.int32)).astype(jnp.int32)
        # pad columns clip to wpe row 0 — garbage the attention mask
        # keeps unread
        pos_ids = jnp.maximum(jnp.arange(T0)[None, :] - start[:, None], 0)
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, T0, d)
    x = x + params["wpe"].astype(cfg.dtype)[pos_ids]
    attn_start = None if lengths is None else start

    def body(x, layer):
        p, = layer
        xa = _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        w = p["attn"]["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (xa @ w).reshape(B, T0, 3, h, hd) \
            + p["attn"]["qkv_b"].astype(cfg.dtype)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = prefill_attention(q, k, v, start=attn_start,
                              use_flash=cfg.use_flash,
                              resident=cfg.flash_resident)
        wo = p["attn"]["o_w"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, T0, h * hd) @ wo
                 + p["attn"]["o_b"].astype(cfg.dtype))
        xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        hmid = jax.nn.gelu(xm @ p["mlp"]["fc_w"].astype(cfg.dtype)
                           + p["mlp"]["fc_b"].astype(cfg.dtype))
        x = x + (hmid @ p["mlp"]["proj_w"].astype(cfg.dtype)
                 + p["mlp"]["proj_b"].astype(cfg.dtype))
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, (params["blocks"],))
    cache["k"] = lax.dynamic_update_slice(cache["k"], ks,
                                          (0, 0, 0, 0, 0))
    cache["v"] = lax.dynamic_update_slice(cache["v"], vs,
                                          (0, 0, 0, 0, 0))
    cache["pos"] = jnp.full((B,), T0, jnp.int32)
    cache["start"] = start
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    last = x[:, -1]                 # left padding ⇒ last real token
    logits = (last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, cache


def decode_step(params, cache, tokens, cfg: GPT2Config
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One token per sequence: tokens (B,) int32, row b at cache slot
    cache["pos"][b] (positions are per-sequence vectors, so rows may
    sit at different depths — ragged prompts, slot-pool serving).

    Returns (logits (B, padded_vocab) float32, updated cache)."""
    B = tokens.shape[0]
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    pos = cache["pos"]                                   # (B,)
    start = cache["start"]                               # (B,)
    rows = jnp.arange(B)
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, d)
    x = x + params["wpe"].astype(cfg.dtype)[pos - start]

    # per-slot mask: start[b] <= s <= pos[b] (current token included)
    attn_mask = slot_mask(start, pos + 1, cfg.max_seq)   # (B, S)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        ck = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)    # (B,S,H,hd)
        cv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        w = p["attn"]["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (xa @ w).reshape(B, 3, h, hd) \
            + p["attn"]["qkv_b"].astype(cfg.dtype)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # (B,h,hd)
        ck = ck.at[rows, pos].set(k_new)       # row b writes slot pos[b]
        cv = cv.at[rows, pos].set(v_new)
        # attention of the single query against the cache
        scores = jnp.einsum("bhd,bshd->bhs", q, ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(attn_mask[:, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bhs,bshd->bhd", probs, cv)       # (B,h,hd)
        wo = p["attn"]["o_w"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, h * hd) @ wo
                 + p["attn"]["o_b"].astype(cfg.dtype))
        xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        hmid = jax.nn.gelu(xm @ p["mlp"]["fc_w"].astype(cfg.dtype)
                           + p["mlp"]["fc_b"].astype(cfg.dtype))
        x = x + (hmid @ p["mlp"]["proj_w"].astype(cfg.dtype)
                 + p["mlp"]["proj_b"].astype(cfg.dtype))
        return (x, lidx + 1), (ck, cv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)
    cache = {"k": new_k, "v": new_v, "pos": pos + 1, "start": start}
    return logits, cache


def _scan_prefill(params, tokens, cfg, *, lengths=None):
    """prefill-shaped wrapper over the per-token reference scan."""
    if lengths is not None:
        raise ValueError("prefill_impl='scan' is the equal-length "
                         "reference path; ragged prompts need the "
                         "batched prefill")
    return scan_prefill(init_cache, decode_step, params, tokens, cfg)


def generate(params, prompt: jnp.ndarray, cfg: GPT2Config, *,
             max_new_tokens: int, temperature: float = 1.0,
             lengths: Optional[jnp.ndarray] = None,
             key: Optional[jax.Array] = None,
             prefill_impl: str = "batched") -> jnp.ndarray:
    """GPT-2 generation (see decode_common.generate_with).  `lengths`
    marks LEFT-padded ragged prompts; prefill_impl="scan" keeps the
    per-token reference prefill for parity testing."""
    prefill_fn = prefill if prefill_impl == "batched" else _scan_prefill
    return generate_with(prefill_fn, decode_step, params, prompt, cfg,
                         max_new_tokens=max_new_tokens,
                         lengths=lengths, temperature=temperature,
                         key=key)
