"""Autoregressive decoding with a KV cache for the GPT-2 family.

The training path (gpt2.py) recomputes full-sequence attention; serving
needs incremental decode: O(1) new compute per token against cached
keys/values.  TPU-first choices:

  * static shapes everywhere — the cache is allocated at max_seq and
    positions beyond `pos` are masked, so ONE compiled step serves the
    whole generation (no shape-polymorphic recompile);
  * the per-token step is a `lax.scan` over the stacked layer params
    with the cache in the carry (same scan-stacked layout as training —
    one layer traced once);
  * generation is itself a `lax.scan` over time: prefill + N sampling
    steps compile into a single dispatch.

No reference analog (the reference wraps user torch modules); this is
the piece that makes ray_tpu.serve a real LM server.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.decode_common import generate_with
from ray_tpu.models.gpt2 import GPT2Config, _layernorm

__all__ = ["init_cache", "decode_step", "generate"]


def init_cache(cfg: GPT2Config, batch: int) -> Dict[str, jnp.ndarray]:
    """Preallocated (L, B, S, H, hd) key/value cache + position 0."""
    if cfg.n_experts:
        raise NotImplementedError(
            "KV-cache decoding currently supports dense GPT-2 configs "
            "only (n_experts=0); MoE decode needs per-step routing")
    shape = (cfg.n_layer, batch, cfg.max_seq, cfg.n_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def decode_step(params, cache, tokens, cfg: GPT2Config
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One token per sequence: tokens (B,) int32 at position cache[pos].

    Returns (logits (B, padded_vocab) float32, updated cache)."""
    B = tokens.shape[0]
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    pos = cache["pos"]
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, d)
    x = x + params["wpe"].astype(cfg.dtype)[pos]

    pos_mask = (jnp.arange(cfg.max_seq) <= pos)          # (S,)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        ck = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)    # (B,S,H,hd)
        cv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        w = p["attn"]["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (xa @ w).reshape(B, 3, h, hd) \
            + p["attn"]["qkv_b"].astype(cfg.dtype)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # (B,h,hd)
        ck = lax.dynamic_update_slice_in_dim(
            ck, k_new[:, None], pos, axis=1)
        cv = lax.dynamic_update_slice_in_dim(
            cv, v_new[:, None], pos, axis=1)
        # attention of the single query against the cache
        scores = jnp.einsum("bhd,bshd->bhs", q, ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(pos_mask[None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bhs,bshd->bhd", probs, cv)       # (B,h,hd)
        wo = p["attn"]["o_w"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, h * hd) @ wo
                 + p["attn"]["o_b"].astype(cfg.dtype))
        xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        hmid = jax.nn.gelu(xm @ p["mlp"]["fc_w"].astype(cfg.dtype)
                           + p["mlp"]["fc_b"].astype(cfg.dtype))
        x = x + (hmid @ p["mlp"]["proj_w"].astype(cfg.dtype)
                 + p["mlp"]["proj_b"].astype(cfg.dtype))
        return (x, lidx + 1), (ck, cv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)
    cache = {"k": new_k, "v": new_v, "pos": pos + 1}
    return logits, cache


def generate(params, prompt: jnp.ndarray, cfg: GPT2Config, *,
             max_new_tokens: int, temperature: float = 1.0,
             key: Optional[jax.Array] = None) -> jnp.ndarray:
    """GPT-2 generation (see generate_with)."""
    return generate_with(init_cache, decode_step, params, prompt, cfg,
                         max_new_tokens=max_new_tokens,
                         temperature=temperature, key=key)
