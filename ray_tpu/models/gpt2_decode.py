"""Autoregressive decoding with a KV cache for the GPT-2 family.

The training path (gpt2.py) recomputes full-sequence attention; serving
needs incremental decode: O(1) new compute per token against cached
keys/values.  TPU-first choices:

  * static shapes everywhere — the cache is allocated at max_seq and
    slots outside [start, pos] are masked, so ONE compiled step serves
    the whole generation (no shape-polymorphic recompile);
  * prompt ingestion is a SINGLE full-sequence forward (`prefill`) that
    reuses the training-path attention (flash kernel where enabled),
    writes K/V for every prompt position with one dynamic_update_slice
    per cache tensor, and computes logits only at each row's last real
    token — O(1) dispatches instead of the old O(T0) per-token scan;
  * positions are per-sequence vectors (decode_common cache contract),
    so LEFT-padded ragged prompts decode correctly in one batch and a
    serve slot pool can host rows at different depths;
  * the per-token step is a `lax.scan` over the stacked layer params
    with the cache in the carry (same scan-stacked layout as training —
    one layer traced once).

No reference analog (the reference wraps user torch modules); this is
the piece that makes ray_tpu.serve a real LM server.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import decode_common
from ray_tpu.models.decode_common import (generate_with, is_paged,
                                          paged_update_and_view,
                                          scan_prefill, slot_mask)
from ray_tpu.models.gpt2 import GPT2Config, _layernorm

__all__ = ["init_cache", "init_paged_cache", "prefill", "paged_prefill",
           "decode_step", "verify_step", "generate"]


def init_cache(cfg: GPT2Config, batch: int,
               mesh=None) -> Dict[str, jnp.ndarray]:
    """Preallocated (L, B, S, H, hd) key/value cache + per-sequence
    position vectors (decode_common cache contract).  With `mesh`, the
    cache is born partitioned (heads over `tensor`; each chip
    allocates only its shard)."""
    if cfg.n_experts:
        raise NotImplementedError(
            "KV-cache decoding currently supports dense GPT-2 configs "
            "only (n_experts=0); MoE decode needs per-step routing")
    shape = (cfg.n_layer, batch, cfg.max_seq, cfg.n_head, cfg.head_dim)

    def build():
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype),
                "pos": jnp.zeros((batch,), jnp.int32),
                "start": jnp.zeros((batch,), jnp.int32)}

    if mesh is None:
        return build()
    return decode_common.partitioned_cache_init(build, mesh)


def init_paged_cache(cfg: GPT2Config, batch: int, *, num_blocks: int,
                     block_size: int,
                     mesh=None) -> Dict[str, jnp.ndarray]:
    """Block-pool cache (decode_common paged contract): K/V pools of
    (L, num_blocks, block_size, H, hd) shared by all rows, per-row
    block tables initialized to the reserved null block 0 (rows hold no
    storage until the pager assigns blocks).  With `mesh`, the pool is
    born partitioned — pool heads split over `tensor`, block tables /
    pos / start replicated so the host pager stays layout-agnostic."""
    if cfg.n_experts:
        raise NotImplementedError(
            "KV-cache decoding currently supports dense GPT-2 configs "
            "only (n_experts=0); MoE decode needs per-step routing")
    if cfg.max_seq % block_size:
        raise ValueError(f"max_seq={cfg.max_seq} must be a multiple of "
                         f"block_size={block_size}")
    shape = (cfg.n_layer, num_blocks, block_size, cfg.n_head,
             cfg.head_dim)

    def build():
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype),
                "block_tables": jnp.zeros(
                    (batch, cfg.max_seq // block_size), jnp.int32),
                "pos": jnp.zeros((batch,), jnp.int32),
                "start": jnp.zeros((batch,), jnp.int32)}

    if mesh is None:
        return build()
    return decode_common.partitioned_cache_init(build, mesh)


def prefill(params, tokens: jnp.ndarray, cfg: GPT2Config, *,
            lengths: Optional[jnp.ndarray] = None
            ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Single-dispatch prompt ingestion: tokens (B, T0) int32 →
    (last_logits (B, padded_vocab) float32, primed cache).

    Runs ONE full-sequence forward (training-path attention; flash
    kernel under the same dispatch rules) and writes K/V for all T0
    positions with one dynamic_update_slice per cache tensor.  Ragged
    batches pass `lengths` (B,): rows are LEFT-padded, so row b's real
    tokens sit at columns [T0 - lengths[b], T0) and the last real token
    is column T0-1 for every row — logits come from that one column,
    never the full (B, T0, V) tensor."""
    from ray_tpu.ops.attention import prefill_attention

    B, T0 = tokens.shape
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    cache = init_cache(cfg, B)
    if lengths is None:
        start = jnp.zeros((B,), jnp.int32)
        pos_ids = jnp.broadcast_to(jnp.arange(T0), (B, T0))
    else:
        start = (T0 - jnp.asarray(lengths, jnp.int32)).astype(jnp.int32)
        # pad columns clip to wpe row 0 — garbage the attention mask
        # keeps unread
        pos_ids = jnp.maximum(jnp.arange(T0)[None, :] - start[:, None], 0)
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, T0, d)
    x = x + params["wpe"].astype(cfg.dtype)[pos_ids]
    attn_start = None if lengths is None else start

    def body(x, layer):
        p, = layer
        xa = _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        w = p["attn"]["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (xa @ w).reshape(B, T0, 3, h, hd) \
            + p["attn"]["qkv_b"].astype(cfg.dtype)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = prefill_attention(q, k, v, start=attn_start,
                              use_flash=cfg.use_flash,
                              resident=cfg.flash_resident)
        wo = p["attn"]["o_w"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, T0, h * hd) @ wo
                 + p["attn"]["o_b"].astype(cfg.dtype))
        xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        hmid = jax.nn.gelu(xm @ p["mlp"]["fc_w"].astype(cfg.dtype)
                           + p["mlp"]["fc_b"].astype(cfg.dtype))
        x = x + (hmid @ p["mlp"]["proj_w"].astype(cfg.dtype)
                 + p["mlp"]["proj_b"].astype(cfg.dtype))
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, (params["blocks"],))
    cache["k"] = lax.dynamic_update_slice(cache["k"], ks,
                                          (0, 0, 0, 0, 0))
    cache["v"] = lax.dynamic_update_slice(cache["v"], vs,
                                          (0, 0, 0, 0, 0))
    cache["pos"] = jnp.full((B,), T0, jnp.int32)
    cache["start"] = start
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    last = x[:, -1]                 # left padding ⇒ last real token
    logits = (last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    return logits, cache


def paged_prefill(params, cache, tokens: jnp.ndarray, cfg: GPT2Config,
                  *, row_bt: jnp.ndarray, prefix_len, n_tail, slot
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Prompt-tail ingestion for ONE sequence against the block pool:
    the prefix-reuse fast path (and, with prefix_len=0, the cold path).

    tokens (1, Tt) int32 is the prompt tail RIGHT-aligned in its bucket
    (left-padded — same convention as the batched prefill, so the last
    real token is always column Tt-1); `n_tail` of them are real and
    land at logical positions [prefix_len, prefix_len + n_tail).
    row_bt (max_seq // block_size,) int32 is the row's full block
    table: entries < prefix_len//bs name already-resident prefix blocks
    whose K/V are read, not recomputed — that is the entire point.
    Tail K/V are scattered into the pool (pad columns route to the
    reserved null block 0); attention for the Tt queries runs against
    the row's gathered pool view with a causal-by-logical-position
    mask.  prefix_len / n_tail / slot are dynamic scalars — one
    compiled program per (Tt bucket, pool shape) serves every request.

    Returns (last-token logits (padded_vocab,) float32, cache with
    pool K/V updated and row `slot`'s table/pos/start set).  Paged
    rows always use start=0 (slot == logical position — the invariant
    that makes blocks shareable across sequences)."""
    _, Tt = tokens.shape
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    bs = cache["k"].shape[2]
    prefix_len = jnp.asarray(prefix_len, jnp.int32)
    n_tail = jnp.asarray(n_tail, jnp.int32)
    pad = Tt - n_tail
    col = jnp.arange(Tt, dtype=jnp.int32)
    real = col >= pad                          # (Tt,), False on pads
    logical = prefix_len + col - pad           # position iff real
    pos_ids = jnp.maximum(logical, 0)          # pads clip to wpe row 0
    # scatter targets for tail K/V: pad columns MUST go to the null
    # block — their logical index can alias a live prefix slot
    blk = jnp.where(real, row_bt[pos_ids // bs], 0)
    off = jnp.where(real, logical % bs, 0)
    # key slot s attendable by query column c iff c is real and
    # s <= logical[c] (all-masked pad columns softmax to uniform —
    # finite garbage that never reaches the pool or the logits)
    mask = real[:, None] & (
        jnp.arange(cfg.max_seq)[None, :] <= logical[:, None])
    scale = 1.0 / math.sqrt(hd)
    x = params["wte"].astype(cfg.dtype)[tokens[0]]       # (Tt, d)
    x = x + params["wpe"].astype(cfg.dtype)[pos_ids]

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        lk = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)    # (nb,bs,H,hd)
        lv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        w = p["attn"]["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (xa @ w).reshape(Tt, 3, h, hd) \
            + p["attn"]["qkv_b"].astype(cfg.dtype)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]        # (Tt,h,hd)
        lk = lk.at[blk, off].set(k)
        lv = lv.at[blk, off].set(v)
        kview = lk[row_bt].reshape(cfg.max_seq, h, hd)
        vview = lv[row_bt].reshape(cfg.max_seq, h, hd)
        scores = jnp.einsum("qhd,khd->hqk", q,
                            kview).astype(jnp.float32) * scale
        scores = jnp.where(mask[None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("hqk,khd->qhd", probs, vview)
        wo = p["attn"]["o_w"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(Tt, h * hd) @ wo
                 + p["attn"]["o_b"].astype(cfg.dtype))
        xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        hmid = jax.nn.gelu(xm @ p["mlp"]["fc_w"].astype(cfg.dtype)
                           + p["mlp"]["fc_b"].astype(cfg.dtype))
        x = x + (hmid @ p["mlp"]["proj_w"].astype(cfg.dtype)
                 + p["mlp"]["proj_b"].astype(cfg.dtype))
        return (x, lidx + 1), (lk, lv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    last = x[-1]                    # right-aligned ⇒ last real token
    logits = (last @ params["wte"].astype(cfg.dtype).T
              ).astype(jnp.float32)
    out = dict(cache)
    out["k"], out["v"] = new_k, new_v
    out["block_tables"] = cache["block_tables"].at[slot].set(row_bt)
    out["pos"] = cache["pos"].at[slot].set(prefix_len + n_tail)
    out["start"] = cache["start"].at[slot].set(0)
    return logits, out


def decode_step(params, cache, tokens, cfg: GPT2Config
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One token per sequence: tokens (B,) int32, row b at cache slot
    cache["pos"][b] (positions are per-sequence vectors, so rows may
    sit at different depths — ragged prompts, slot-pool serving).

    Works on both cache layouts (the pytree structure is the knob —
    decode_common.is_paged): dense caches index a (B, S, ...) layer and
    write slot pos[b]; paged caches scatter into the row's pool block
    and attend over the gathered block-table view, which is
    value-identical to the dense layer, so everything downstream of the
    K/V update is shared verbatim between layouts.

    Returns (logits (B, padded_vocab) float32, updated cache)."""
    B = tokens.shape[0]
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    paged = is_paged(cache)
    pos = cache["pos"]                                   # (B,)
    start = cache["start"]                               # (B,)
    rows = jnp.arange(B)
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, d)
    x = x + params["wpe"].astype(cfg.dtype)[pos - start]

    # per-slot mask: start[b] <= s <= pos[b] (current token included)
    attn_mask = slot_mask(start, pos + 1, cfg.max_seq)   # (B, S)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        lk = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)
        lv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        w = p["attn"]["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (xa @ w).reshape(B, 3, h, hd) \
            + p["attn"]["qkv_b"].astype(cfg.dtype)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # (B,h,hd)
        if paged:
            bt = cache["block_tables"]
            lk, ck = paged_update_and_view(lk, bt, pos, k_new)
            lv, cv = paged_update_and_view(lv, bt, pos, v_new)
        else:
            lk = ck = lk.at[rows, pos].set(k_new)  # row b → slot pos[b]
            lv = cv = lv.at[rows, pos].set(v_new)
        # attention of the single query against the cache
        scores = jnp.einsum("bhd,bshd->bhs", q, ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(attn_mask[:, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bhs,bshd->bhd", probs, cv)       # (B,h,hd)
        wo = p["attn"]["o_w"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, h * hd) @ wo
                 + p["attn"]["o_b"].astype(cfg.dtype))
        xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        hmid = jax.nn.gelu(xm @ p["mlp"]["fc_w"].astype(cfg.dtype)
                           + p["mlp"]["fc_b"].astype(cfg.dtype))
        x = x + (hmid @ p["mlp"]["proj_w"].astype(cfg.dtype)
                 + p["mlp"]["proj_b"].astype(cfg.dtype))
        return (x, lidx + 1), (lk, lv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)
    out = dict(cache)
    out["k"], out["v"], out["pos"] = new_k, new_v, pos + 1
    return logits, out


def verify_step(params, cache, block, cfg: GPT2Config
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Speculative-decode verify forward: T=k+1 tokens per row in ONE
    dispatch (round 11).  block (B, T) int32 is [cur, d_1..d_k] — the
    last sampled-but-not-yet-ingested token followed by the draft's k
    proposals; row b's t-th token lands at cache slot pos[b] + t, and
    logits[:, t] is the target's distribution for the token AFTER
    block[:, t] — exactly what T sequential decode_step dispatches
    would produce, which is what makes greedy spec decode bit-exact
    against the non-speculative oracle.

    Shares decode_step's per-slot masking discipline (the PR 2 ragged
    prefill shape: per-row pos/start, causal within the block) and
    both KV layouts.  Writes past max_seq — possible only in a
    request's final rounds, when the accepted prefix can't reach them
    anyway — are routed to the null block (paged) or dropped (dense)
    instead of clamping onto live slots.  pos is NOT advanced: the
    caller (decode_common.make_spec_verify) moves it by the accepted
    count, which IS the rollback."""
    B, T = block.shape
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    paged = is_paged(cache)
    pos = cache["pos"]                                   # (B,)
    start = cache["start"]                               # (B,)
    rows = jnp.arange(B)
    offs = jnp.arange(T, dtype=jnp.int32)
    slot_ids = pos[:, None] + offs[None, :]              # (B, T)
    in_range = slot_ids < cfg.max_seq
    pos_ids = jnp.minimum(jnp.maximum(slot_ids - start[:, None], 0),
                          cfg.max_seq - 1)
    x = params["wte"].astype(cfg.dtype)[block]           # (B, T, d)
    x = x + params["wpe"].astype(cfg.dtype)[pos_ids]
    # (B, T, S): query t attends slots start[b] <= s <= pos[b] + t
    s = jnp.arange(cfg.max_seq)
    attn_mask = (s[None, None, :] >= start[:, None, None]) & \
                (s[None, None, :] <= slot_ids[:, :, None])
    if paged:
        bt = cache["block_tables"]
        bs = cache["k"].shape[2]
        blk_col = jnp.minimum(slot_ids // bs, bt.shape[1] - 1)
        blk = jnp.where(in_range, bt[rows[:, None], blk_col], 0)
        off = jnp.where(in_range, slot_ids % bs, 0)
    else:
        # OOB rows dropped by the scatter (mode="drop")
        write_idx = jnp.where(in_range, slot_ids, cfg.max_seq)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        lk = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)
        lv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"])
        w = p["attn"]["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
        qkv = (xa @ w).reshape(B, T, 3, h, hd) \
            + p["attn"]["qkv_b"].astype(cfg.dtype)
        q, k_new, v_new = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if paged:
            lk = lk.at[blk, off].set(k_new)
            lv = lv.at[blk, off].set(v_new)
            ck = lk[bt].reshape(B, cfg.max_seq, h, hd)
            cv = lv[bt].reshape(B, cfg.max_seq, h, hd)
        else:
            lk = ck = lk.at[rows[:, None], write_idx].set(
                k_new, mode="drop")
            lv = cv = lv.at[rows[:, None], write_idx].set(
                v_new, mode="drop")
        scores = jnp.einsum("bthd,bshd->bhts", q,
                            ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(attn_mask[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bhts,bshd->bthd", probs, cv)     # (B,T,h,hd)
        wo = p["attn"]["o_w"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, T, h * hd) @ wo
                 + p["attn"]["o_b"].astype(cfg.dtype))
        xm = _layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"])
        hmid = jax.nn.gelu(xm @ p["mlp"]["fc_w"].astype(cfg.dtype)
                           + p["mlp"]["fc_b"].astype(cfg.dtype))
        x = x + (hmid @ p["mlp"]["proj_w"].astype(cfg.dtype)
                 + p["mlp"]["proj_b"].astype(cfg.dtype))
        return (x, lidx + 1), (lk, lv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = (x @ params["wte"].astype(cfg.dtype).T).astype(jnp.float32)
    out = dict(cache)
    out["k"], out["v"] = new_k, new_v
    return logits, out


def _scan_prefill(params, tokens, cfg, *, lengths=None):
    """prefill-shaped wrapper over the per-token reference scan."""
    if lengths is not None:
        raise ValueError("prefill_impl='scan' is the equal-length "
                         "reference path; ragged prompts need the "
                         "batched prefill")
    return scan_prefill(init_cache, decode_step, params, tokens, cfg)


def generate(params, prompt: jnp.ndarray, cfg: GPT2Config, *,
             max_new_tokens: int, temperature: float = 1.0,
             top_k: int = 0, top_p: float = 1.0,
             lengths: Optional[jnp.ndarray] = None,
             key: Optional[jax.Array] = None,
             prefill_impl: str = "batched",
             kv_layout: str = "dense",
             kv_block_size: int = 16) -> jnp.ndarray:
    """GPT-2 generation (see decode_common.generate_with).  `lengths`
    marks LEFT-padded ragged prompts; prefill_impl="scan" keeps the
    per-token reference prefill for parity testing; kv_layout="paged"
    decodes through the block-pool layout (dense is its oracle);
    top_k/top_p are jit-static sampling filters."""
    prefill_fn = prefill if prefill_impl == "batched" else _scan_prefill
    return generate_with(prefill_fn, decode_step, params, prompt, cfg,
                         max_new_tokens=max_new_tokens,
                         lengths=lengths, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         key=key, kv_layout=kv_layout,
                         kv_block_size=kv_block_size)
