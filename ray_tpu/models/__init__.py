"""Model zoo: TPU-first reference models driven by ray_tpu.train.

Pure-functional JAX (init/apply pairs over pytrees), layers stacked for
`lax.scan`, parameters annotated with logical sharding axes
(ray_tpu.parallel.sharding) so one model definition serves DP, FSDP, TP,
and sequence parallelism by swapping the rule table.
"""

from ray_tpu.models.gpt2 import (GPT2Config, gpt2_config, gpt2_forward,
                                 gpt2_init, gpt2_logical_axes, gpt2_loss,
                                 gpt2_param_count)
from ray_tpu.models.gpt2_decode import (decode_step, generate,
                                        init_cache, prefill)
from ray_tpu.models.llama import (LlamaConfig, llama_config,
                                  llama_forward, llama_init,
                                  llama_logical_axes, llama_loss,
                                  llama_param_count)
from ray_tpu.models.llama_decode import (llama_decode_step,
                                         llama_generate,
                                         llama_init_cache,
                                         llama_prefill)
from ray_tpu.models.moe import (MoEConfig, moe_apply, moe_init,
                                moe_logical_axes)
from ray_tpu.models.mlp import (MLPConfig, mlp_forward, mlp_init,
                                mlp_logical_axes, mlp_loss)
from ray_tpu.models.resnet import (ResNetConfig, resnet_config,
                                   resnet_forward, resnet_init,
                                   resnet_logical_axes, resnet_loss)
from ray_tpu.models.vit import (ViTConfig, vit_config, vit_forward,
                                vit_init, vit_logical_axes, vit_loss,
                                vit_param_count)

__all__ = [
    "GPT2Config", "gpt2_config", "gpt2_init", "gpt2_forward", "gpt2_loss",
    "gpt2_logical_axes", "gpt2_param_count", "init_cache", "decode_step",
    "generate", "prefill",
    "MLPConfig", "mlp_init", "mlp_forward", "mlp_loss", "mlp_logical_axes",
    "MoEConfig", "moe_init", "moe_apply", "moe_logical_axes",
    "ResNetConfig", "resnet_config", "resnet_init", "resnet_forward",
    "resnet_loss", "resnet_logical_axes",
    "ViTConfig", "vit_config", "vit_init", "vit_forward", "vit_loss",
    "vit_logical_axes", "vit_param_count",
    "LlamaConfig", "llama_config", "llama_init", "llama_forward",
    "llama_loss", "llama_logical_axes", "llama_param_count",
    "llama_init_cache", "llama_decode_step", "llama_generate",
    "llama_prefill",
]
