"""Vision Transformer (ViT), TPU-first.

Completes the model zoo's image-transformer family alongside GPT-2
(text) and ResNet (conv).  The reference ships no models (it wraps user
torch modules, train/torch/train_loop_utils.py:28); this exists because
on TPU the compute path is the framework's value.

Same design rules as gpt2.py: functional init/apply over a pytree,
layers stacked on a leading axis under `lax.scan`, bf16 compute / f32
params, logical sharding axes reusing the SAME rule table (embed->fsdp,
heads/mlp->tensor), projections in flattened-GEMM form (the 5-D einsum
lowers 10x slower on v5e — see gpt2._attention), and the pallas flash
kernel for attention when profitable (non-causal here).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.sharding import DEFAULT_RULES, with_logical_constraint

_PRESETS = {
    # name: (n_layer, n_head, d_model, patch)
    "tiny": (2, 2, 64, 8),            # test-sized
    "vit-s16": (12, 6, 384, 16),
    "vit-b16": (12, 12, 768, 16),
    "vit-l16": (24, 16, 1024, 16),
}


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    n_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    use_flash: Optional[bool] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def vit_config(name: str = "vit-b16", **overrides) -> ViTConfig:
    n_layer, n_head, d_model, patch = _PRESETS[name]
    kw: Dict[str, Any] = dict(n_layer=n_layer, n_head=n_head,
                              d_model=d_model, d_ff=4 * d_model,
                              patch_size=patch)
    if name == "tiny":
        kw.update(image_size=32, n_classes=10)
    kw.update(overrides)
    return ViTConfig(**kw)


def vit_param_count(cfg: ViTConfig) -> int:
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layer
    per_layer = (4 * d * d + 4 * d) + (2 * d * f + d + f) + 4 * d
    patch_in = cfg.patch_size ** 2 * 3
    return (patch_in * d + d                       # patch embed
            + (cfg.n_patches + 1) * d + d          # pos emb + cls
            + L * per_layer + 2 * d                # blocks + final ln
            + d * cfg.n_classes + cfg.n_classes)   # head


def vit_logical_axes(cfg: ViTConfig) -> Dict[str, Any]:
    return {
        "patch_w": (None, "embed"),
        "patch_b": ("embed",),
        "pos": (None, "embed"),
        "cls": (None, "embed"),
        "ln_f": {"scale": ("embed",), "bias": ("embed",)},
        "head_w": ("embed", None),
        "head_b": (None,),
        "blocks": {
            "ln1": {"scale": (None, "embed"), "bias": (None, "embed")},
            "ln2": {"scale": (None, "embed"), "bias": (None, "embed")},
            "attn": {
                "qkv_w": (None, "embed", None, "heads", "head_dim"),
                "qkv_b": (None, None, "heads", "head_dim"),
                "o_w": (None, "heads", "head_dim", "embed"),
                "o_b": (None, "embed"),
            },
            "mlp": {
                "fc_w": (None, "embed", "mlp"),
                "fc_b": (None, "mlp"),
                "proj_w": (None, "mlp", "embed"),
                "proj_b": (None, "embed"),
            },
        },
    }


def vit_init(key, cfg: ViTConfig) -> Dict[str, Any]:
    L, d, f, h, hd = (cfg.n_layer, cfg.d_model, cfg.d_ff, cfg.n_head,
                      cfg.head_dim)
    pd = cfg.param_dtype
    k = iter(jax.random.split(key, 10))
    std = 0.02
    res_std = std / math.sqrt(2 * L)
    patch_in = cfg.patch_size ** 2 * 3

    def norm(kk, shape, s=std):
        return (jax.random.normal(kk, shape, dtype=jnp.float32) * s
                ).astype(pd)

    return {
        "patch_w": norm(next(k), (patch_in, d),
                        s=1.0 / math.sqrt(patch_in)),
        "patch_b": jnp.zeros((d,), pd),
        "pos": norm(next(k), (cfg.n_patches + 1, d), s=0.01),
        "cls": jnp.zeros((1, d), pd),
        "ln_f": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
        "head_w": jnp.zeros((d, cfg.n_classes), pd),  # ViT: zero-init head
        "head_b": jnp.zeros((cfg.n_classes,), pd),
        "blocks": {
            "ln1": {"scale": jnp.ones((L, d), pd),
                    "bias": jnp.zeros((L, d), pd)},
            "ln2": {"scale": jnp.ones((L, d), pd),
                    "bias": jnp.zeros((L, d), pd)},
            "attn": {
                "qkv_w": norm(next(k), (L, d, 3, h, hd)),
                "qkv_b": jnp.zeros((L, 3, h, hd), pd),
                "o_w": norm(next(k), (L, h, hd, d), s=res_std),
                "o_b": jnp.zeros((L, d), pd),
            },
            "mlp": {
                "fc_w": norm(next(k), (L, d, f)),
                "fc_b": jnp.zeros((L, f), pd),
                "proj_w": norm(next(k), (L, f, d), s=res_std),
                "proj_b": jnp.zeros((L, d), pd),
            },
        },
    }


def _layernorm(x, scale, bias, eps=1e-6):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _attention(x, p, cfg: ViTConfig, rules):
    B, T, d = x.shape
    h, hd = cfg.n_head, cfg.head_dim
    w = p["qkv_w"].astype(cfg.dtype).reshape(d, 3 * h * hd)
    qkv = (x @ w).reshape(B, T, 3, h, hd) + p["qkv_b"].astype(cfg.dtype)
    q, kk, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = with_logical_constraint(q, ("batch", "seq", "heads", "head_dim"),
                                rules)
    use_flash = cfg.use_flash
    if use_flash is None:
        from ray_tpu.ops.attention import flash_auto_dispatch

        use_flash = flash_auto_dispatch(T, hd)
    if use_flash:
        from ray_tpu.ops.flash_attention import flash_attention

        o = flash_attention(q, kk, v, causal=False)
    else:
        from ray_tpu.ops.attention import reference_attention

        o = reference_attention(q, kk, v, causal=False)
    wo = p["o_w"].astype(cfg.dtype).reshape(h * hd, d)
    return o.reshape(B, T, h * hd) @ wo + p["o_b"].astype(cfg.dtype)


def _mlp(x, p, cfg: ViTConfig, rules):
    hd = jax.nn.gelu(x @ p["fc_w"].astype(cfg.dtype)
                     + p["fc_b"].astype(cfg.dtype))
    hd = with_logical_constraint(hd, ("batch", "seq", "mlp"), rules)
    return hd @ p["proj_w"].astype(cfg.dtype) + p["proj_b"].astype(cfg.dtype)


def _block(x, p, cfg: ViTConfig, rules):
    x = x + _attention(_layernorm(x, p["ln1"]["scale"], p["ln1"]["bias"]),
                       p["attn"], cfg, rules)
    x = x + _mlp(_layernorm(x, p["ln2"]["scale"], p["ln2"]["bias"]),
                 p["mlp"], cfg, rules)
    return with_logical_constraint(x, ("batch", "seq", "embed"), rules)


def vit_forward(params, images, cfg: ViTConfig,
                rules=DEFAULT_RULES) -> jnp.ndarray:
    """images (B, H, W, 3) float → logits (B, n_classes) float32."""
    B, H, W, C = images.shape
    ps = cfg.patch_size
    # patchify as one reshape+GEMM (the TPU-friendly conv-free form):
    # (B, H/ps, ps, W/ps, ps, C) -> (B, N, ps*ps*C) @ (ps*ps*C, d)
    x = images.astype(cfg.dtype).reshape(B, H // ps, ps, W // ps, ps, C)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, cfg.n_patches,
                                              ps * ps * C)
    x = x @ params["patch_w"].astype(cfg.dtype) \
        + params["patch_b"].astype(cfg.dtype)
    cls = jnp.broadcast_to(params["cls"].astype(cfg.dtype),
                           (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos"].astype(cfg.dtype)
    x = with_logical_constraint(x, ("batch", "seq", "embed"), rules)

    block = partial(_block, cfg=cfg, rules=rules)
    if cfg.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)

    def scan_body(carry, layer_params):
        return block(carry, layer_params), None

    x, _ = lax.scan(scan_body, x, params["blocks"])
    x = _layernorm(x[:, 0], params["ln_f"]["scale"],
                   params["ln_f"]["bias"])  # CLS token
    return (x @ params["head_w"].astype(cfg.dtype)
            + params["head_b"].astype(cfg.dtype)).astype(jnp.float32)


def vit_loss(params, batch, cfg: ViTConfig,
             rules=DEFAULT_RULES) -> jnp.ndarray:
    """batch: {"images": (B,H,W,3), "labels": (B,)} → mean CE loss."""
    logits = vit_forward(params, batch["images"], cfg, rules)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None],
                               axis=-1)[:, 0]
    return jnp.mean(nll)
