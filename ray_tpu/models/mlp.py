"""MLP classifier — the fashion-MNIST baseline config (BASELINE.json:
"DataParallelTrainer: fashion-MNIST MLP (2 CPU workers)") and the smoke
model for trainer tests."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Sequence[int] = (128, 128)
    n_classes: int = 10
    dtype: Any = jnp.float32


def mlp_logical_axes(cfg: MLPConfig) -> Dict[str, Any]:
    n = len(cfg.hidden) + 1
    return {"layers": [{"w": ("embed", "mlp"), "b": ("mlp",)}
                       for _ in range(n)]}


def mlp_init(key, cfg: MLPConfig) -> Dict[str, Any]:
    dims = [cfg.in_dim, *cfg.hidden, cfg.n_classes]
    layers = []
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (d_in, d_out), cfg.dtype)
        layers.append({"w": w * jnp.sqrt(2.0 / d_in),
                       "b": jnp.zeros((d_out,), cfg.dtype)})
    return {"layers": layers}


def mlp_forward(params, x, cfg: MLPConfig) -> jnp.ndarray:
    x = x.reshape(x.shape[0], -1).astype(cfg.dtype)
    layers = params["layers"]
    for layer in layers[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    return x @ layers[-1]["w"] + layers[-1]["b"]


def mlp_loss(params, batch, cfg: MLPConfig) -> jnp.ndarray:
    logits = mlp_forward(params, batch["x"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    labels = batch["y"]
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
