"""Mixture-of-Experts feed-forward block, expert-parallel over the
``expert`` mesh axis.

No reference analog (SURVEY §2d: EP absent upstream) — new TPU-first
design completing the mesh axis table.  Dense capacity-based dispatch in
the Switch/GShard style: routing builds one-hot dispatch/combine tensors
and the expert computation is three einsums, so under GSPMD the
``expert``-sharded dims turn into all-to-alls on ICI and the per-expert
matmuls stay MXU-shaped.  No data-dependent shapes — everything is
static for XLA.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import DEFAULT_RULES, with_logical_constraint


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 256
    d_ff: int = 512
    n_experts: int = 4
    top_k: int = 2
    #: capacity per expert = ceil(tokens/experts) * capacity_factor
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def moe_init(key, cfg: MoEConfig) -> Dict[str, Any]:
    kg, k1, k2 = jax.random.split(key, 3)
    pd = cfg.param_dtype
    E, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff
    s = 0.02

    def norm(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    return {
        "gate": norm(kg, (d, E)),
        "w1": norm(k1, (E, d, f)),
        "b1": jnp.zeros((E, f), pd),
        "w2": norm(k2, (E, f, d)),
        "b2": jnp.zeros((E, d), pd),
    }


def moe_logical_axes(cfg: MoEConfig) -> Dict[str, Tuple]:
    return {
        "gate": ("embed", None),
        "w1": ("expert", "embed", "mlp"),
        "b1": ("expert", "mlp"),
        "w2": ("expert", "mlp", "embed"),
        "b2": ("expert", "embed"),
    }


def moe_apply(params, x: jnp.ndarray, cfg: MoEConfig,
              rules=DEFAULT_RULES) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, T, d) -> ((B, T, d), aux_loss).

    aux_loss is the GShard/Switch load-balancing term — add
    ``aux_weight * aux_loss`` (typical 1e-2) to the training loss.
    """
    B, T, d = x.shape
    E, K = cfg.n_experts, cfg.top_k
    N = B * T
    # Each token makes K assignments, so balanced load is K*N/E per
    # expert (GShard capacity definition).
    C = int(math.ceil(N * K / E * cfg.capacity_factor))
    xf = x.reshape(N, d)

    # Routing in float32 for a stable softmax.
    logits = jnp.einsum("nd,de->ne", xf.astype(jnp.float32),
                        params["gate"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # (N, E)

    # Top-k expert choice per token -> dispatch (N,E,C) and combine
    # weights, built with static shapes only.
    remaining = probs
    dispatch = jnp.zeros((N, E), jnp.float32)
    combine = jnp.zeros((N, E), jnp.float32)
    for _ in range(K):
        idx = jnp.argmax(remaining, axis=-1)               # (N,)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        combine = combine + onehot * probs
        dispatch = dispatch + onehot
        remaining = remaining * (1.0 - onehot)

    # Capacity: position of each token in its expert's queue; overflow
    # tokens are dropped (their combine weight zeroes out — the residual
    # stream carries them unchanged).
    position = jnp.cumsum(dispatch, axis=0) * dispatch - 1.0   # (N, E)
    keep = (position >= 0) & (position < C)
    dispatch = dispatch * keep
    combine = combine * keep
    slot = jax.nn.one_hot(position.astype(jnp.int32), C,
                          dtype=jnp.float32)                   # (N, E, C)
    disp = dispatch[..., None] * slot                          # (N, E, C)
    comb = combine[..., None] * slot                           # (N, E, C)

    # Expert compute: (E, C, d) inputs, sharded over the expert axis —
    # GSPMD turns the resharding into an all-to-all.
    exp_in = jnp.einsum("nec,nd->ecd", disp.astype(cfg.dtype),
                        xf.astype(cfg.dtype))
    exp_in = with_logical_constraint(exp_in, ("expert", None, "embed"),
                                     rules)
    h = jnp.einsum("ecd,edf->ecf", exp_in, params["w1"].astype(cfg.dtype))
    h = jax.nn.gelu(h + params["b1"].astype(cfg.dtype)[:, None, :])
    h = with_logical_constraint(h, ("expert", None, "mlp"), rules)
    out = jnp.einsum("ecf,efd->ecd", h, params["w2"].astype(cfg.dtype))
    out = out + params["b2"].astype(cfg.dtype)[:, None, :]
    y = jnp.einsum("nec,ecd->nd", comb.astype(cfg.dtype), out)

    # Load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e.
    token_frac = jnp.mean(dispatch, axis=0)          # fraction routed
    prob_frac = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(token_frac * prob_frac) * (1.0 / K)
    return y.reshape(B, T, d).astype(x.dtype), aux.astype(jnp.float32)
