"""LLaMA-family decoder: RMSNorm + RoPE + SwiGLU + grouped-query
attention.

Same TPU-first template as gpt2.py (the reference framework ships no
models — this zoo exists because on TPU the framework owns the compute
path): pure init/apply over pytrees, layers stacked on a leading axis
and applied with one `lax.scan`, parameters annotated with logical
sharding axes so DP/FSDP/TP/SP come from the rule table, attention
dispatching to the pallas flash kernel, per-layer remat.

Architecture (Touvron et al. 2023 / the llama-2 lineage, public):
  * pre-RMSNorm (no biases anywhere),
  * rotary position embeddings applied to q/k (no learned positions),
  * SwiGLU MLP (gate ⊙ silu(up) → down, d_ff ≈ 8/3·d rounded),
  * grouped-query attention: n_kv_head ≤ n_head kv heads shared by
    query groups (kv repeated head-wise before the kernel — exact, and
    the repeat is free under the flash kernel's (B·H, T, D) layout).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.gpt2 import (ce_config_problems, lm_head_nll,
                                 nll_from_logits)
from ray_tpu.parallel.sharding import (DEFAULT_RULES,
                                       with_logical_constraint)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    max_seq: int = 2048
    n_layer: int = 8
    n_head: int = 8
    n_kv_head: int = 4
    d_model: int = 512
    d_ff: int = 1408              # ≈ 8/3 · d, rounded to a 128-multiple
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    scan_unroll: int = 1
    use_flash: Optional[bool] = None    # None = auto (flash on TPU)
    vocab_pad_to: int = 128
    #: lm-head + CE implementation (gpt2.CE_IMPLS); the non-dense impls
    #: run against the TRANSPOSED (V, D) view of lm_head so one kernel
    #: serves tied and untied heads (the transpose+cast fuses into the
    #: bf16 tile staging — cheap next to the (B,T,V) logits it removes).
    ce_impl: str = "dense"
    vocab_tile: int = 8192
    ce_block_n: int = 256
    ce_block_v: int = 1024
    #: resident-kv flash dispatch knob (gpt2.FLASH_RESIDENT_MODES);
    #: RAYTPU_FLASH_RESIDENT overrides per-process.
    flash_resident: str = "auto"

    def __post_init__(self):
        problems = ce_config_problems(self.ce_impl, self.flash_resident)
        if problems:
            raise ValueError("invalid LlamaConfig: "
                             + "; ".join(problems))

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def padded_vocab(self) -> int:
        p = self.vocab_pad_to
        return (self.vocab_size + p - 1) // p * p


_PRESETS = {
    # name: (n_layer, n_head, n_kv_head, d_model, d_ff)
    "nano": (2, 2, 1, 64, 192),
    "tiny": (4, 4, 2, 128, 384),
    "llama-s": (12, 12, 4, 768, 2048),     # GPT-2-small-class
    "llama-1b": (16, 32, 8, 2048, 5504),
    "llama-7b": (32, 32, 32, 4096, 11008),
}


def llama_config(name: str = "llama-s", **overrides) -> LlamaConfig:
    L, h, kv, d, f = _PRESETS[name]
    kw: Dict[str, Any] = dict(n_layer=L, n_head=h, n_kv_head=kv,
                              d_model=d, d_ff=f)
    if name in ("nano", "tiny"):
        kw.update(vocab_size=512, max_seq=128)
    kw.update(overrides)
    cfg = LlamaConfig(**kw)
    if cfg.n_head % cfg.n_kv_head:
        raise ValueError(f"n_head {cfg.n_head} must divide by "
                         f"n_kv_head {cfg.n_kv_head}")
    return cfg


def llama_param_count(cfg: LlamaConfig) -> int:
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layer
    hd = cfg.head_dim
    attn = d * cfg.n_head * hd + 2 * d * cfg.n_kv_head * hd \
        + cfg.n_head * hd * d
    mlp = 3 * d * f
    per_layer = attn + mlp + 2 * d          # + two rmsnorm scales
    return 2 * cfg.vocab_size * d + L * per_layer + d


def llama_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Pytree (matching llama_init's) of logical-axis tuples; leading
    None on block leaves is the stacked-layer axis."""
    return {
        "wte": ("vocab", "embed"),
        "lm_head": ("embed", "vocab"),
        "ln_f": {"scale": ("embed",)},
        "blocks": {
            "ln1": {"scale": (None, "embed")},
            "ln2": {"scale": (None, "embed")},
            "attn": {
                "wq": (None, "embed", "heads", "head_dim"),
                "wk": (None, "embed", "kv_heads", "head_dim"),
                "wv": (None, "embed", "kv_heads", "head_dim"),
                "wo": (None, "heads", "head_dim", "embed"),
            },
            "mlp": {
                "w_gate": (None, "embed", "mlp"),
                "w_up": (None, "embed", "mlp"),
                "w_down": (None, "mlp", "embed"),
            },
        },
    }


def llama_init(key, cfg: LlamaConfig) -> Dict[str, Any]:
    L, d, f = cfg.n_layer, cfg.d_model, cfg.d_ff
    h, kv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    pd = cfg.param_dtype
    k = iter(jax.random.split(key, 12))
    std = 0.02
    res_std = std / math.sqrt(2 * L)

    def norm(kk, shape, s=std):
        return (jax.random.normal(kk, shape, dtype=jnp.float32)
                * s).astype(pd)

    return {
        "wte": norm(next(k), (cfg.padded_vocab, d)),
        "lm_head": norm(next(k), (d, cfg.padded_vocab)),
        "ln_f": {"scale": jnp.ones((d,), pd)},
        "blocks": {
            "ln1": {"scale": jnp.ones((L, d), pd)},
            "ln2": {"scale": jnp.ones((L, d), pd)},
            "attn": {
                "wq": norm(next(k), (L, d, h, hd)),
                "wk": norm(next(k), (L, d, kv, hd)),
                "wv": norm(next(k), (L, d, kv, hd)),
                "wo": norm(next(k), (L, h, hd, d), s=res_std),
            },
            "mlp": {
                "w_gate": norm(next(k), (L, d, f)),
                "w_up": norm(next(k), (L, d, f)),
                "w_down": norm(next(k), (L, f, d), s=res_std),
            },
        },
    }


def _rmsnorm(x, scale, eps):
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1,
                                keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def rope_frequencies(T: int, head_dim: int, theta: float):
    """(T, head_dim/2) cos/sin tables."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    ang = jnp.arange(T, dtype=jnp.float32)[:, None] * inv[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """x (B, T, H, D) with D even; rotate pairs (x_2i, x_2i+1)."""
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    r1 = x1 * c - x2 * s
    r2 = x1 * s + x2 * c
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _attention(x, p, cos, sin, cfg: LlamaConfig, rules):
    B, T, d = x.shape
    h, kv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    xc = x.astype(cfg.dtype)
    # flattened GEMMs (the measured-fast TPU form; see gpt2._attention)
    q = (xc @ p["wq"].astype(cfg.dtype).reshape(d, h * hd)
         ).reshape(B, T, h, hd)
    k = (xc @ p["wk"].astype(cfg.dtype).reshape(d, kv * hd)
         ).reshape(B, T, kv, hd)
    v = (xc @ p["wv"].astype(cfg.dtype).reshape(d, kv * hd)
         ).reshape(B, T, kv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if kv != h:
        # GQA: each kv head serves h/kv query heads; the head-wise
        # repeat is exact and lays out contiguously for the kernel
        rep = h // kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    q = with_logical_constraint(q, ("batch", "seq", "heads",
                                    "head_dim"), rules)
    from ray_tpu.ops.attention import causal_attention

    o = causal_attention(q, k, v, use_flash=cfg.use_flash,
                         resident=cfg.flash_resident)
    o = o.reshape(B, T, h * hd)
    wo = p["wo"].astype(cfg.dtype).reshape(h * hd, d)
    return (o @ wo).astype(x.dtype)


def _mlp(x, p, cfg: LlamaConfig, rules):
    xc = x.astype(cfg.dtype)
    gate = xc @ p["w_gate"].astype(cfg.dtype)
    up = xc @ p["w_up"].astype(cfg.dtype)
    hidden = jax.nn.silu(gate) * up
    hidden = with_logical_constraint(hidden, ("batch", "seq", "mlp"),
                                     rules)
    return (hidden @ p["w_down"].astype(cfg.dtype)).astype(x.dtype)


def _block(x, p, cos, sin, cfg: LlamaConfig, rules):
    x = x + _attention(_rmsnorm(x, p["ln1"]["scale"], cfg.rms_eps),
                       p["attn"], cos, sin, cfg, rules)
    x = x + _mlp(_rmsnorm(x, p["ln2"]["scale"], cfg.rms_eps),
                 p["mlp"], cfg, rules)
    return with_logical_constraint(x, ("batch", "seq", "embed"),
                                   rules), None


def llama_hidden(params, tokens, cfg: LlamaConfig,
                 rules=DEFAULT_RULES):
    B, T = tokens.shape
    wte = with_logical_constraint(params["wte"].astype(cfg.dtype),
                                  (None, None), rules)
    x = wte[tokens]
    x = with_logical_constraint(x, ("batch", "seq", "embed"), rules)
    cos, sin = rope_frequencies(T, cfg.head_dim, cfg.rope_theta)

    block = partial(_block, cos=cos, sin=sin, cfg=cfg, rules=rules)
    if cfg.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)

    def scan_body(carry, layer_params):
        return block(carry, layer_params)

    x, _ = lax.scan(scan_body, x, params["blocks"],
                    unroll=cfg.scan_unroll)
    return _rmsnorm(x, params["ln_f"]["scale"], cfg.rms_eps)


def llama_forward(params, tokens, cfg: LlamaConfig,
                  rules=DEFAULT_RULES) -> jnp.ndarray:
    """tokens (B, T) int32 → logits (B, T, padded_vocab) float32."""
    x = llama_hidden(params, tokens, cfg, rules)
    logits = jnp.einsum("btd,dv->btv", x,
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return with_logical_constraint(logits, ("batch", "seq", "vocab"),
                                   rules)


def llama_loss(params, batch, cfg: LlamaConfig,
               rules=DEFAULT_RULES) -> jnp.ndarray:
    """Next-token cross-entropy; batch = {"tokens": (B, T+1)} or
    {"inputs", "targets"}; padded-vocab tail masked (the gather-free
    NLL shared with gpt2)."""
    if "tokens" in batch:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    if cfg.ce_impl != "dense":
        hidden = llama_hidden(params, inputs, cfg, rules)
        # (D, V) lm_head → the (V, D) vocab-major view the CE kernels
        # share with gpt2's tied wte
        nll = lm_head_nll(hidden, params["lm_head"].T, targets, cfg)
    else:
        logits = llama_forward(params, inputs, cfg, rules)
        nll = nll_from_logits(logits, targets, cfg.vocab_size,
                              cfg.padded_vocab)
    mask = batch.get("mask")
    if mask is not None:
        m = mask.astype(jnp.float32)
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(nll)
