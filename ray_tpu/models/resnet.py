"""ResNet (v1.5, post-activation) — the image-training baseline config
(BASELINE.json: "JaxTrainer: ResNet-50 ImageNet data-parallel").

TPU-first choices: NHWC layout (the TPU-native conv layout), bfloat16
compute with float32 batch-norm statistics, channels padded-friendly
widths (all multiples of 64), functional batch-norm carrying running
stats in a separate `state` pytree so the train step stays pure.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_STAGES = {
    # name: (block sizes, bottleneck?)
    "resnet18": ((2, 2, 2, 2), False),
    "resnet34": ((3, 4, 6, 3), False),
    "resnet50": ((3, 4, 6, 3), True),
    "resnet101": ((3, 4, 23, 3), True),
    "tiny": ((1, 1), False),  # test-sized: 2 stages
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    blocks: Sequence[int] = (3, 4, 6, 3)
    bottleneck: bool = True
    n_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9


def resnet_config(name: str = "resnet50", **overrides) -> ResNetConfig:
    blocks, bottleneck = _STAGES[name]
    kw: Dict[str, Any] = dict(blocks=blocks, bottleneck=bottleneck)
    if name == "tiny":
        kw.update(width=32, n_classes=10)
    kw.update(overrides)
    return ResNetConfig(**kw)


def _conv_init(key, kh, kw_, cin, cout, dtype):
    fan_in = kh * kw_ * cin
    w = jax.random.normal(key, (kh, kw_, cin, cout), jnp.float32)
    return (w * jnp.sqrt(2.0 / fan_in)).astype(dtype)


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def _block_channels(cfg: ResNetConfig) -> List[Tuple[int, int, int]]:
    """(cin, cmid, cout) per residual block, flattened over stages."""
    out: List[Tuple[int, int, int]] = []
    expansion = 4 if cfg.bottleneck else 1
    cin = cfg.width
    for stage, n in enumerate(cfg.blocks):
        cmid = cfg.width * (2 ** stage)
        cout = cmid * expansion
        for _ in range(n):
            out.append((cin, cmid, cout))
            cin = cout
    return out


def resnet_init(key, cfg: ResNetConfig):
    """Returns (params, state): state holds BN running stats."""
    keys = iter(jax.random.split(key, 4 + 4 * sum(cfg.blocks)))
    pd = cfg.param_dtype
    params: Dict[str, Any] = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, cfg.width, pd),
                 "bn": _bn_init(cfg.width, pd)},
        "blocks": [],
        "head": {},
    }
    state: Dict[str, Any] = {"stem": _bn_state(cfg.width), "blocks": []}
    for i, (cin, cmid, cout) in enumerate(_block_channels(cfg)):
        if cfg.bottleneck:
            convs = [_conv_init(next(keys), 1, 1, cin, cmid, pd),
                     _conv_init(next(keys), 3, 3, cmid, cmid, pd),
                     _conv_init(next(keys), 1, 1, cmid, cout, pd)]
            bns = [_bn_init(cmid, pd), _bn_init(cmid, pd),
                   _bn_init(cout, pd)]
            sts = [_bn_state(cmid), _bn_state(cmid), _bn_state(cout)]
        else:
            convs = [_conv_init(next(keys), 3, 3, cin, cmid, pd),
                     _conv_init(next(keys), 3, 3, cmid, cout, pd)]
            bns = [_bn_init(cmid, pd), _bn_init(cout, pd)]
            sts = [_bn_state(cmid), _bn_state(cout)]
        blk = {"convs": convs, "bns": bns}
        st = {"bns": sts}
        if cin != cout:
            blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout, pd)
            blk["proj_bn"] = _bn_init(cout, pd)
            st["proj_bn"] = _bn_state(cout)
        params["blocks"].append(blk)
        state["blocks"].append(st)
    chead = _block_channels(cfg)[-1][2]
    kh = next(keys)
    params["head"] = {
        "w": (jax.random.normal(kh, (chead, cfg.n_classes), jnp.float32)
              * 0.01).astype(pd),
        "b": jnp.zeros((cfg.n_classes,), pd),
    }
    return params, state


def resnet_logical_axes(cfg: ResNetConfig):
    """Conv kernels shard cout over tensor, cin over fsdp (HWIO layout)."""
    conv_ax = (None, None, "embed", "mlp")
    bn_ax = {"scale": ("norm",), "bias": ("norm",)}
    axes: Dict[str, Any] = {
        "stem": {"conv": conv_ax, "bn": bn_ax},
        "blocks": [],
        "head": {"w": ("embed", "vocab"), "b": ("vocab",)},
    }
    for blk_ch, blk in zip(_block_channels(cfg), _params_blocks(cfg)):
        b: Dict[str, Any] = {"convs": [conv_ax] * blk["n"],
                             "bns": [bn_ax] * blk["n"]}
        if blk["proj"]:
            b["proj"] = conv_ax
            b["proj_bn"] = bn_ax
        axes["blocks"].append(b)
    return axes


def _params_blocks(cfg: ResNetConfig):
    n = 3 if cfg.bottleneck else 2
    out = []
    for (cin, _, cout) in _block_channels(cfg):
        out.append({"n": n, "proj": cin != cout})
    return out


def _batchnorm(x, p, st, *, training: bool, momentum: float):
    xf = x.astype(jnp.float32)
    if training:
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        new_st = {"mean": momentum * st["mean"] + (1 - momentum) * mean,
                  "var": momentum * st["var"] + (1 - momentum) * var}
    else:
        mean, var = st["mean"], st["var"]
        new_st = st
    y = (xf - mean) * lax.rsqrt(var + 1e-5)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype), new_st


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def resnet_forward(params, state, images, cfg: ResNetConfig, *,
                   training: bool = True):
    """images (B, H, W, 3) → (logits (B, n_classes), new_state)."""
    x = images.astype(cfg.dtype)
    mom = cfg.bn_momentum
    new_state: Dict[str, Any] = {"blocks": []}

    x = _conv(x, params["stem"]["conv"], stride=2)
    x, new_state["stem"] = _batchnorm(x, params["stem"]["bn"],
                                      state["stem"], training=training,
                                      momentum=mom)
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")

    chans = _block_channels(cfg)
    stage_starts = set()
    acc = 0
    for n in cfg.blocks:
        stage_starts.add(acc)
        acc += n

    for i, (blk, st, (cin, cmid, cout)) in enumerate(
            zip(params["blocks"], state["blocks"], chans)):
        stride = 2 if (i in stage_starts and i != 0) else 1
        shortcut = x
        new_blk: Dict[str, Any] = {"bns": []}
        strides = ([1, stride, 1] if cfg.bottleneck else [stride, 1])
        h = x
        for j, (w, bn, bst, s) in enumerate(
                zip(blk["convs"], blk["bns"], st["bns"], strides)):
            h = _conv(h, w, stride=s)
            h, nst = _batchnorm(h, bn, bst, training=training, momentum=mom)
            new_blk["bns"].append(nst)
            if j < len(blk["convs"]) - 1:
                h = jax.nn.relu(h)
        if "proj" in blk:
            shortcut = _conv(shortcut, blk["proj"], stride=stride)
            shortcut, nst = _batchnorm(shortcut, blk["proj_bn"],
                                       st["proj_bn"], training=training,
                                       momentum=mom)
            new_blk["proj_bn"] = nst
        x = jax.nn.relu(h + shortcut)
        new_state["blocks"].append(new_blk)

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["head"]["w"].astype(jnp.float32) + \
        params["head"]["b"].astype(jnp.float32)
    return logits, new_state


def resnet_loss(params, state, batch, cfg: ResNetConfig, *,
                training: bool = True):
    logits, new_state = resnet_forward(params, state, batch["x"], cfg,
                                       training=training)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["y"][:, None], axis=-1)
    return jnp.mean(nll), new_state
