"""Autoregressive KV-cache decoding for the LLaMA family.

Same TPU-first shape as gpt2_decode (static max_seq cache, one compiled
per-token step scanned over stacked layers, generation itself a scan),
adapted to the llama block: RMSNorm, RoPE applied at the live position,
grouped-query attention (the cache stores the kv heads only — GQA's
memory win is exactly here: cache bytes scale with n_kv_head, not
n_head), SwiGLU, untied lm_head.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import (LlamaConfig, _rmsnorm,
                                  rope_frequencies)

__all__ = ["llama_init_cache", "llama_decode_step", "llama_generate"]


def llama_init_cache(cfg: LlamaConfig, batch: int
                     ) -> Dict[str, jnp.ndarray]:
    """(L, B, S, n_kv_head, hd) key/value cache + position 0."""
    shape = (cfg.n_layer, batch, cfg.max_seq, cfg.n_kv_head,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def _rope_at(x, cos_t, sin_t):
    """Rotate (B, H, hd) by the tables' row for ONE position."""
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos_t[None, None, :]
    s = sin_t[None, None, :]
    out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c],
                    axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def llama_decode_step(params, cache, tokens, cfg: LlamaConfig
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One token per sequence: tokens (B,) int32 at cache['pos'].

    Returns (logits (B, padded_vocab) float32, updated cache)."""
    B = tokens.shape[0]
    d, h, kv, hd = (cfg.d_model, cfg.n_head, cfg.n_kv_head,
                    cfg.head_dim)
    g = h // kv
    pos = cache["pos"]
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, d)
    cos, sin = rope_frequencies(cfg.max_seq, hd, cfg.rope_theta)
    cos_t = lax.dynamic_index_in_dim(cos, pos, keepdims=False)
    sin_t = lax.dynamic_index_in_dim(sin, pos, keepdims=False)
    pos_mask = (jnp.arange(cfg.max_seq) <= pos)          # (S,)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        ck = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)    # (B,S,kv,hd)
        cv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _rmsnorm(x, p["ln1"]["scale"], cfg.rms_eps)
        xa = xa.astype(cfg.dtype)
        q = (xa @ p["attn"]["wq"].astype(cfg.dtype).reshape(d, h * hd)
             ).reshape(B, h, hd)
        k_new = (xa @ p["attn"]["wk"].astype(cfg.dtype)
                 .reshape(d, kv * hd)).reshape(B, kv, hd)
        v_new = (xa @ p["attn"]["wv"].astype(cfg.dtype)
                 .reshape(d, kv * hd)).reshape(B, kv, hd)
        q = _rope_at(q, cos_t, sin_t)
        k_new = _rope_at(k_new, cos_t, sin_t)
        ck = lax.dynamic_update_slice_in_dim(
            ck, k_new[:, None], pos, axis=1)
        cv = lax.dynamic_update_slice_in_dim(
            cv, v_new[:, None], pos, axis=1)
        # grouped-query attention against the kv-head cache: query
        # heads reshape to (kv, group) — no head repetition needed
        qg = q.reshape(B, kv, g, hd)
        scores = jnp.einsum("bkgd,bskd->bkgs", qg,
                            ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(pos_mask[None, None, None, :], scores,
                           -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bkgs,bskd->bkgd", probs, cv)
        wo = p["attn"]["wo"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, h * hd) @ wo).astype(x.dtype)
        xm = _rmsnorm(x, p["ln2"]["scale"], cfg.rms_eps)
        xm = xm.astype(cfg.dtype)
        gate = xm @ p["mlp"]["w_gate"].astype(cfg.dtype)
        up = xm @ p["mlp"]["w_up"].astype(cfg.dtype)
        hmid = jax.nn.silu(gate) * up
        x = x + (hmid @ p["mlp"]["w_down"].astype(cfg.dtype)
                 ).astype(x.dtype)
        return (x, lidx + 1), (ck, cv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _rmsnorm(x, params["ln_f"]["scale"], cfg.rms_eps)
    logits = (x.astype(cfg.dtype)
              @ params["lm_head"].astype(cfg.dtype)
              ).astype(jnp.float32)
    cache = {"k": new_k, "v": new_v, "pos": pos + 1}
    return logits, cache


def llama_generate(params, prompt: jnp.ndarray, cfg: LlamaConfig, *,
                   max_new_tokens: int, temperature: float = 1.0,
                   key: Optional[jax.Array] = None) -> jnp.ndarray:
    """LLaMA generation via the shared loop (decode_common.generate_with)."""
    from ray_tpu.models.decode_common import generate_with

    return generate_with(llama_init_cache, llama_decode_step, params,
                         prompt, cfg, max_new_tokens=max_new_tokens,
                         temperature=temperature, key=key)
