"""Autoregressive KV-cache decoding for the LLaMA family.

Same TPU-first shape as gpt2_decode (static max_seq cache, single
full-sequence `llama_prefill` dispatch, one compiled per-token step
scanned over stacked layers, per-sequence position vectors for ragged
batches), adapted to the llama block: RMSNorm, RoPE applied at each
row's live position, grouped-query attention (the cache stores the kv
heads only — GQA's memory win is exactly here: cache bytes scale with
n_kv_head, not n_head), SwiGLU, untied lm_head.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import decode_common
from ray_tpu.models.decode_common import (generate_with, is_paged,
                                          paged_update_and_view,
                                          scan_prefill, slot_mask)
from ray_tpu.models.llama import (LlamaConfig, _rmsnorm,
                                  rope_frequencies)

__all__ = ["llama_init_cache", "llama_init_paged_cache",
           "llama_prefill", "llama_paged_prefill", "llama_decode_step",
           "llama_verify_step", "llama_generate"]


def llama_init_cache(cfg: LlamaConfig, batch: int,
                     mesh=None) -> Dict[str, jnp.ndarray]:
    """(L, B, S, n_kv_head, hd) key/value cache + per-sequence position
    vectors (decode_common cache contract).  With `mesh`, the cache is
    born partitioned — KV heads over `tensor` when n_kv_head divides
    the tensor degree, replicated otherwise (GQA guard)."""
    shape = (cfg.n_layer, batch, cfg.max_seq, cfg.n_kv_head,
             cfg.head_dim)

    def build():
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype),
                "pos": jnp.zeros((batch,), jnp.int32),
                "start": jnp.zeros((batch,), jnp.int32)}

    if mesh is None:
        return build()
    return decode_common.partitioned_cache_init(build, mesh)


def llama_init_paged_cache(cfg: LlamaConfig, batch: int, *,
                           num_blocks: int, block_size: int,
                           mesh=None) -> Dict[str, jnp.ndarray]:
    """Block-pool cache (decode_common paged contract): K/V pools of
    (L, num_blocks, block_size, n_kv_head, hd) shared by all rows,
    per-row block tables initialized to the reserved null block 0.
    With `mesh`, the pool is born partitioned (see llama_init_cache;
    tables/pos/start stay replicated for the host pager)."""
    if cfg.max_seq % block_size:
        raise ValueError(f"max_seq={cfg.max_seq} must be a multiple of "
                         f"block_size={block_size}")
    shape = (cfg.n_layer, num_blocks, block_size, cfg.n_kv_head,
             cfg.head_dim)

    def build():
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype),
                "block_tables": jnp.zeros(
                    (batch, cfg.max_seq // block_size), jnp.int32),
                "pos": jnp.zeros((batch,), jnp.int32),
                "start": jnp.zeros((batch,), jnp.int32)}

    if mesh is None:
        return build()
    return decode_common.partitioned_cache_init(build, mesh)


def _rope_at(x, cos_t, sin_t):
    """Rotate (B, H, hd) by per-row table rows (B, hd/2)."""
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos_t[:, None, :]
    s = sin_t[:, None, :]
    out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c],
                    axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _rope_bt(x, cos_bt, sin_bt):
    """Rotate (B, T, H, hd) by per-row, per-column tables (B, T, hd/2)
    — the ragged-prefill variant of llama.apply_rope, whose (T, hd/2)
    tables assume every row shares the same position ladder."""
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos_bt[:, :, None, :]
    s = sin_bt[:, :, None, :]
    out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c],
                    axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def llama_prefill(params, tokens: jnp.ndarray, cfg: LlamaConfig, *,
                  lengths: Optional[jnp.ndarray] = None
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Single-dispatch prompt ingestion: tokens (B, T0) int32 →
    (last_logits (B, padded_vocab) float32, primed cache).

    One full-sequence forward (training-path attention; flash kernel
    under the same dispatch rules on the equal-length path), K/V for
    all T0 positions written with one dynamic_update_slice per cache
    tensor — the cache keeps kv heads only (pre-repeat, post-RoPE),
    exactly what llama_decode_step expects.  Ragged rows are
    LEFT-padded with `lengths` (B,); RoPE angles follow each row's
    logical positions, so pads never shift a real token's rotation."""
    from ray_tpu.ops.attention import prefill_attention

    B, T0 = tokens.shape
    d, h, kv, hd = (cfg.d_model, cfg.n_head, cfg.n_kv_head,
                    cfg.head_dim)
    cache = llama_init_cache(cfg, B)
    if lengths is None:
        start = jnp.zeros((B,), jnp.int32)
        pos_ids = jnp.broadcast_to(jnp.arange(T0), (B, T0))
    else:
        start = (T0 - jnp.asarray(lengths, jnp.int32)).astype(jnp.int32)
        pos_ids = jnp.maximum(jnp.arange(T0)[None, :] - start[:, None], 0)
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, T0, d)
    cos, sin = rope_frequencies(cfg.max_seq, hd, cfg.rope_theta)
    cos_p, sin_p = cos[pos_ids], sin[pos_ids]            # (B, T0, hd/2)
    attn_start = None if lengths is None else start

    def body(x, layer):
        p, = layer
        xa = _rmsnorm(x, p["ln1"]["scale"], cfg.rms_eps)
        xa = xa.astype(cfg.dtype)
        q = (xa @ p["attn"]["wq"].astype(cfg.dtype).reshape(d, h * hd)
             ).reshape(B, T0, h, hd)
        k = (xa @ p["attn"]["wk"].astype(cfg.dtype).reshape(d, kv * hd)
             ).reshape(B, T0, kv, hd)
        v = (xa @ p["attn"]["wv"].astype(cfg.dtype).reshape(d, kv * hd)
             ).reshape(B, T0, kv, hd)
        q = _rope_bt(q, cos_p, sin_p)
        k = _rope_bt(k, cos_p, sin_p)
        if kv != h:
            rep = h // kv
            kr = jnp.repeat(k, rep, axis=2)
            vr = jnp.repeat(v, rep, axis=2)
        else:
            kr, vr = k, v
        o = prefill_attention(q, kr, vr, start=attn_start,
                              use_flash=cfg.use_flash,
                              resident=cfg.flash_resident)
        wo = p["attn"]["wo"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, T0, h * hd) @ wo).astype(x.dtype)
        xm = _rmsnorm(x, p["ln2"]["scale"], cfg.rms_eps)
        xm = xm.astype(cfg.dtype)
        gate = xm @ p["mlp"]["w_gate"].astype(cfg.dtype)
        up = xm @ p["mlp"]["w_up"].astype(cfg.dtype)
        hmid = jax.nn.silu(gate) * up
        x = x + (hmid @ p["mlp"]["w_down"].astype(cfg.dtype)
                 ).astype(x.dtype)
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, (params["blocks"],))
    cache["k"] = lax.dynamic_update_slice(cache["k"], ks,
                                          (0, 0, 0, 0, 0))
    cache["v"] = lax.dynamic_update_slice(cache["v"], vs,
                                          (0, 0, 0, 0, 0))
    cache["pos"] = jnp.full((B,), T0, jnp.int32)
    cache["start"] = start
    x = _rmsnorm(x, params["ln_f"]["scale"], cfg.rms_eps)
    last = x[:, -1]                 # left padding ⇒ last real token
    logits = (last.astype(cfg.dtype)
              @ params["lm_head"].astype(cfg.dtype)
              ).astype(jnp.float32)
    return logits, cache


def llama_paged_prefill(params, cache, tokens: jnp.ndarray,
                        cfg: LlamaConfig, *, row_bt: jnp.ndarray,
                        prefix_len, n_tail, slot
                        ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Prompt-tail ingestion for ONE sequence against the block pool
    (see gpt2_decode.paged_prefill for the full contract): tokens
    (1, Tt) RIGHT-aligned tail, prefix K/V read from resident pool
    blocks via row_bt, tail K/V (post-RoPE, kv heads only) scattered in
    (pads → null block 0).  RoPE follows logical positions, and the
    kv heads are repeated to n_head for attention exactly as in
    llama_prefill so the hidden states match the dense path."""
    _, Tt = tokens.shape
    d, h, kv, hd = (cfg.d_model, cfg.n_head, cfg.n_kv_head,
                    cfg.head_dim)
    bs = cache["k"].shape[2]
    prefix_len = jnp.asarray(prefix_len, jnp.int32)
    n_tail = jnp.asarray(n_tail, jnp.int32)
    pad = Tt - n_tail
    col = jnp.arange(Tt, dtype=jnp.int32)
    real = col >= pad                          # (Tt,), False on pads
    logical = prefix_len + col - pad           # position iff real
    pos_ids = jnp.maximum(logical, 0)          # pads clip to position 0
    # pad columns MUST scatter to the null block — their logical index
    # can alias a live prefix slot
    blk = jnp.where(real, row_bt[pos_ids // bs], 0)
    off = jnp.where(real, logical % bs, 0)
    mask = real[:, None] & (
        jnp.arange(cfg.max_seq)[None, :] <= logical[:, None])
    scale = 1.0 / math.sqrt(hd)
    x = params["wte"].astype(cfg.dtype)[tokens[0]]       # (Tt, d)
    cos, sin = rope_frequencies(cfg.max_seq, hd, cfg.rope_theta)
    cos_p, sin_p = cos[pos_ids], sin[pos_ids]            # (Tt, hd/2)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        lk = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)    # (nb,bs,kv,hd)
        lv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _rmsnorm(x, p["ln1"]["scale"], cfg.rms_eps)
        xa = xa.astype(cfg.dtype)
        q = (xa @ p["attn"]["wq"].astype(cfg.dtype).reshape(d, h * hd)
             ).reshape(Tt, h, hd)
        k = (xa @ p["attn"]["wk"].astype(cfg.dtype).reshape(d, kv * hd)
             ).reshape(Tt, kv, hd)
        v = (xa @ p["attn"]["wv"].astype(cfg.dtype).reshape(d, kv * hd)
             ).reshape(Tt, kv, hd)
        q = _rope_at(q, cos_p, sin_p)
        k = _rope_at(k, cos_p, sin_p)
        lk = lk.at[blk, off].set(k)
        lv = lv.at[blk, off].set(v)
        kview = lk[row_bt].reshape(cfg.max_seq, kv, hd)
        vview = lv[row_bt].reshape(cfg.max_seq, kv, hd)
        if kv != h:
            rep = h // kv
            kview = jnp.repeat(kview, rep, axis=1)
            vview = jnp.repeat(vview, rep, axis=1)
        scores = jnp.einsum("qhd,khd->hqk", q,
                            kview).astype(jnp.float32) * scale
        scores = jnp.where(mask[None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("hqk,khd->qhd", probs, vview)
        wo = p["attn"]["wo"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(Tt, h * hd) @ wo).astype(x.dtype)
        xm = _rmsnorm(x, p["ln2"]["scale"], cfg.rms_eps)
        xm = xm.astype(cfg.dtype)
        gate = xm @ p["mlp"]["w_gate"].astype(cfg.dtype)
        up = xm @ p["mlp"]["w_up"].astype(cfg.dtype)
        hmid = jax.nn.silu(gate) * up
        x = x + (hmid @ p["mlp"]["w_down"].astype(cfg.dtype)
                 ).astype(x.dtype)
        return (x, lidx + 1), (lk, lv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _rmsnorm(x, params["ln_f"]["scale"], cfg.rms_eps)
    last = x[-1]                    # right-aligned ⇒ last real token
    logits = (last.astype(cfg.dtype)
              @ params["lm_head"].astype(cfg.dtype)
              ).astype(jnp.float32)
    out = dict(cache)
    out["k"], out["v"] = new_k, new_v
    out["block_tables"] = cache["block_tables"].at[slot].set(row_bt)
    out["pos"] = cache["pos"].at[slot].set(prefix_len + n_tail)
    out["start"] = cache["start"].at[slot].set(0)
    return logits, out


def llama_decode_step(params, cache, tokens, cfg: LlamaConfig
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One token per sequence: tokens (B,) int32, row b at cache slot
    cache["pos"][b]; RoPE at each row's LOGICAL position pos - start.

    Works on both cache layouts (decode_common.is_paged): dense caches
    write slot pos[b] in a (B, S, ...) layer; paged caches scatter into
    the row's pool block and attend over the gathered block-table view
    (value-identical to dense, so the attention math is shared).

    Returns (logits (B, padded_vocab) float32, updated cache)."""
    B = tokens.shape[0]
    d, h, kv, hd = (cfg.d_model, cfg.n_head, cfg.n_kv_head,
                    cfg.head_dim)
    g = h // kv
    paged = is_paged(cache)
    pos = cache["pos"]                                   # (B,)
    start = cache["start"]                               # (B,)
    rows = jnp.arange(B)
    x = params["wte"].astype(cfg.dtype)[tokens]          # (B, d)
    cos, sin = rope_frequencies(cfg.max_seq, hd, cfg.rope_theta)
    cos_t, sin_t = cos[pos - start], sin[pos - start]    # (B, hd/2)
    attn_mask = slot_mask(start, pos + 1, cfg.max_seq)   # (B, S)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        lk = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)
        lv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _rmsnorm(x, p["ln1"]["scale"], cfg.rms_eps)
        xa = xa.astype(cfg.dtype)
        q = (xa @ p["attn"]["wq"].astype(cfg.dtype).reshape(d, h * hd)
             ).reshape(B, h, hd)
        k_new = (xa @ p["attn"]["wk"].astype(cfg.dtype)
                 .reshape(d, kv * hd)).reshape(B, kv, hd)
        v_new = (xa @ p["attn"]["wv"].astype(cfg.dtype)
                 .reshape(d, kv * hd)).reshape(B, kv, hd)
        q = _rope_at(q, cos_t, sin_t)
        k_new = _rope_at(k_new, cos_t, sin_t)
        if paged:
            bt = cache["block_tables"]
            lk, ck = paged_update_and_view(lk, bt, pos, k_new)
            lv, cv = paged_update_and_view(lv, bt, pos, v_new)
        else:
            lk = ck = lk.at[rows, pos].set(k_new)  # row b → slot pos[b]
            lv = cv = lv.at[rows, pos].set(v_new)
        # grouped-query attention against the kv-head cache: query
        # heads reshape to (kv, group) — no head repetition needed
        qg = q.reshape(B, kv, g, hd)
        scores = jnp.einsum("bkgd,bskd->bkgs", qg,
                            ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(attn_mask[:, None, None, :], scores,
                           -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bkgs,bskd->bkgd", probs, cv)
        wo = p["attn"]["wo"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, h * hd) @ wo).astype(x.dtype)
        xm = _rmsnorm(x, p["ln2"]["scale"], cfg.rms_eps)
        xm = xm.astype(cfg.dtype)
        gate = xm @ p["mlp"]["w_gate"].astype(cfg.dtype)
        up = xm @ p["mlp"]["w_up"].astype(cfg.dtype)
        hmid = jax.nn.silu(gate) * up
        x = x + (hmid @ p["mlp"]["w_down"].astype(cfg.dtype)
                 ).astype(x.dtype)
        return (x, lidx + 1), (lk, lv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _rmsnorm(x, params["ln_f"]["scale"], cfg.rms_eps)
    logits = (x.astype(cfg.dtype)
              @ params["lm_head"].astype(cfg.dtype)
              ).astype(jnp.float32)
    out = dict(cache)
    out["k"], out["v"], out["pos"] = new_k, new_v, pos + 1
    return logits, out


def llama_verify_step(params, cache, block, cfg: LlamaConfig
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Speculative-decode verify forward, llama flavour (see
    gpt2_decode.verify_step for the shared contract): block (B, T=k+1)
    int32 = [cur, d_1..d_k], one dispatch producing logits (B, T,
    padded_vocab) equal to T sequential llama_decode_step calls.  RoPE
    rotates each (row, column) at its own logical position via the
    per-row-per-column tables (_rope_bt); GQA attends through the
    kv-head cache with the (kv, group) query reshape.  Writes past
    max_seq route to the null block (paged) / drop (dense); pos is NOT
    advanced — make_spec_verify moves it by the accepted count."""
    B, T = block.shape
    d, h, kv, hd = (cfg.d_model, cfg.n_head, cfg.n_kv_head,
                    cfg.head_dim)
    g = h // kv
    paged = is_paged(cache)
    pos = cache["pos"]                                   # (B,)
    start = cache["start"]                               # (B,)
    rows = jnp.arange(B)
    offs = jnp.arange(T, dtype=jnp.int32)
    slot_ids = pos[:, None] + offs[None, :]              # (B, T)
    in_range = slot_ids < cfg.max_seq
    pos_ids = jnp.minimum(jnp.maximum(slot_ids - start[:, None], 0),
                          cfg.max_seq - 1)
    x = params["wte"].astype(cfg.dtype)[block]           # (B, T, d)
    cos, sin = rope_frequencies(cfg.max_seq, hd, cfg.rope_theta)
    cos_p, sin_p = cos[pos_ids], sin[pos_ids]            # (B, T, hd/2)
    s = jnp.arange(cfg.max_seq)
    attn_mask = (s[None, None, :] >= start[:, None, None]) & \
                (s[None, None, :] <= slot_ids[:, :, None])
    if paged:
        bt = cache["block_tables"]
        bs = cache["k"].shape[2]
        blk_col = jnp.minimum(slot_ids // bs, bt.shape[1] - 1)
        blk = jnp.where(in_range, bt[rows[:, None], blk_col], 0)
        off = jnp.where(in_range, slot_ids % bs, 0)
    else:
        write_idx = jnp.where(in_range, slot_ids, cfg.max_seq)

    def body(carry, layer):
        x, lidx = carry
        p, = layer
        lk = lax.dynamic_index_in_dim(cache["k"], lidx, axis=0,
                                      keepdims=False)
        lv = lax.dynamic_index_in_dim(cache["v"], lidx, axis=0,
                                      keepdims=False)
        xa = _rmsnorm(x, p["ln1"]["scale"], cfg.rms_eps)
        xa = xa.astype(cfg.dtype)
        q = (xa @ p["attn"]["wq"].astype(cfg.dtype).reshape(d, h * hd)
             ).reshape(B, T, h, hd)
        k_new = (xa @ p["attn"]["wk"].astype(cfg.dtype)
                 .reshape(d, kv * hd)).reshape(B, T, kv, hd)
        v_new = (xa @ p["attn"]["wv"].astype(cfg.dtype)
                 .reshape(d, kv * hd)).reshape(B, T, kv, hd)
        q = _rope_bt(q, cos_p, sin_p)
        k_new = _rope_bt(k_new, cos_p, sin_p)
        if paged:
            lk = lk.at[blk, off].set(k_new)
            lv = lv.at[blk, off].set(v_new)
            ck = lk[bt].reshape(B, cfg.max_seq, kv, hd)
            cv = lv[bt].reshape(B, cfg.max_seq, kv, hd)
        else:
            lk = ck = lk.at[rows[:, None], write_idx].set(
                k_new, mode="drop")
            lv = cv = lv.at[rows[:, None], write_idx].set(
                v_new, mode="drop")
        qg = q.reshape(B, T, kv, g, hd)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg,
                            ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        scores = jnp.where(attn_mask[:, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bkgts,bskd->btkgd", probs, cv)
        wo = p["attn"]["wo"].astype(cfg.dtype).reshape(h * hd, d)
        x = x + (o.reshape(B, T, h * hd) @ wo).astype(x.dtype)
        xm = _rmsnorm(x, p["ln2"]["scale"], cfg.rms_eps)
        xm = xm.astype(cfg.dtype)
        gate = xm @ p["mlp"]["w_gate"].astype(cfg.dtype)
        up = xm @ p["mlp"]["w_up"].astype(cfg.dtype)
        hmid = jax.nn.silu(gate) * up
        x = x + (hmid @ p["mlp"]["w_down"].astype(cfg.dtype)
                 ).astype(x.dtype)
        return (x, lidx + 1), (lk, lv)

    (x, _), (new_k, new_v) = lax.scan(body, (x, jnp.int32(0)),
                                      (params["blocks"],))
    x = _rmsnorm(x, params["ln_f"]["scale"], cfg.rms_eps)
    logits = (x.astype(cfg.dtype)
              @ params["lm_head"].astype(cfg.dtype)
              ).astype(jnp.float32)
    out = dict(cache)
    out["k"], out["v"] = new_k, new_v
    return logits, out


def _scan_prefill(params, tokens, cfg, *, lengths=None):
    """prefill-shaped wrapper over the per-token reference scan."""
    if lengths is not None:
        raise ValueError("prefill_impl='scan' is the equal-length "
                         "reference path; ragged prompts need the "
                         "batched prefill")
    return scan_prefill(llama_init_cache, llama_decode_step, params,
                        tokens, cfg)


def llama_generate(params, prompt: jnp.ndarray, cfg: LlamaConfig, *,
                   max_new_tokens: int, temperature: float = 1.0,
                   top_k: int = 0, top_p: float = 1.0,
                   lengths: Optional[jnp.ndarray] = None,
                   key: Optional[jax.Array] = None,
                   prefill_impl: str = "batched",
                   kv_layout: str = "dense",
                   kv_block_size: int = 16) -> jnp.ndarray:
    """LLaMA generation via the shared loop (decode_common).  `lengths`
    marks LEFT-padded ragged prompts; prefill_impl="scan" keeps the
    per-token reference prefill for parity testing; kv_layout="paged"
    decodes through the block-pool layout (dense is its oracle);
    top_k/top_p are jit-static sampling filters."""
    prefill_fn = (llama_prefill if prefill_impl == "batched"
                  else _scan_prefill)
    return generate_with(prefill_fn, llama_decode_step, params, prompt,
                         cfg, max_new_tokens=max_new_tokens,
                         lengths=lengths, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         key=key, kv_layout=kv_layout,
                         kv_block_size=kv_block_size)
