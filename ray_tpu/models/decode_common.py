"""The generation machinery shared by every decoder family.

Family modules (gpt2_decode, llama_decode) supply their
(init_cache_fn, prefill_fn, decode_step_fn) triple; this module owns
the family-neutral prefill dispatch + sampling scan so fixes land once.

Cache contract (vector positions, round 7 — ragged batches decode
together):

  k, v  : (L, B, S, ...) preallocated at cfg.max_seq
  pos   : (B,) int32 — next cache slot each sequence writes
  start : (B,) int32 — first valid slot (the left-pad offset); the
          LOGICAL position of the token at slot s is s - start[b], so
          the next token's wpe/RoPE index is pos[b] - start[b]

The per-slot attention mask is derived, not stored: slot s is
attendable for row b iff start[b] <= s <= pos[b] (after the current
token's K/V lands at slot pos[b]).  Equal-length prompts are the
degenerate case start == 0.

Paged layout (round 8 — block-paged KV with prefix reuse): instead of
dense per-row (L, B, S, ...) cache tensors, K/V live in a shared pool
of fixed-size blocks

  k, v          : (L, num_blocks, block_size, ...) preallocated pool
  block_tables  : (B, S // block_size) int32 — row b's j-th table entry
                  names the pool block holding slots
                  [j*block_size, (j+1)*block_size); block 0 is the
                  reserved null/trash block (never allocated, absorbs
                  masked pad writes)
  pos, start    : unchanged

The jitted decode step reads the cache through a gather by block id
(one layer at a time inside the layer scan — never the whole dense
cache at once) and writes the new token with a scatter at
(block_tables[b, pos//bs], pos % bs).  Because table entries are kept
in sequence order, the gathered view is value-identical to the dense
layout, so attention numerics are bit-identical between layouts — the
dense path stays the parity oracle (same pattern as
prefill_impl="scan").  Host-side block allocation / refcounting /
prefix hashing lives in ray_tpu/serve/kv_pager.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Jit-static sampling knobs (round 11).

    Frozen + hashable on purpose: the serve engine keys its compiled
    program cache on this object, so two engines (or two requests)
    with different knobs can never alias one stale XLA program.
    top_k=0 disables the top-k filter; top_p=1.0 disables nucleus
    filtering; temperature 0 is greedy (filters become no-ops since
    argmax of a superset equals argmax of the kept set's union with
    -inf tails).
    """
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")


def slot_mask(start: jnp.ndarray, end: jnp.ndarray,
              max_seq: int) -> jnp.ndarray:
    """(B, S) bool — cache slots holding attendable K/V per row:
    start[b] <= s < end[b] (end exclusive)."""
    s = jnp.arange(max_seq)
    return (s[None, :] >= start[:, None]) & (s[None, :] < end[:, None])


def is_paged(cache) -> bool:
    """The cache pytree itself is the layout knob: a pool cache carries
    a block table, a dense cache doesn't.  Static under jit (pytree
    structure), so the python branch costs nothing."""
    return "block_tables" in cache


def paged_update_and_view(layer, block_tables, pos, new):
    """One decode-step K (or V) update against a paged pool layer.

    layer (num_blocks, bs, H, hd) is one layer's block pool;
    block_tables (B, max_blk) int32; pos (B,) int32; new (B, H, hd).
    Writes new[b] into block block_tables[b, pos[b]//bs] at offset
    pos[b] % bs (every active row's tail block is private, so the
    scatter is conflict-free), then gathers each row's blocks into the
    dense-equivalent (B, max_blk*bs, H, hd) attention view.  Table
    entries are sequence-ordered, so view[b, s] holds exactly what the
    dense cache would hold at slot s — unattended slots carry other
    sequences' bytes, but the slot mask replaces them with the same
    -1e30 the dense path writes, keeping logits bit-identical."""
    bs = layer.shape[1]
    rows = jnp.arange(block_tables.shape[0])
    blk = block_tables[rows, pos // bs]
    layer = layer.at[blk, pos % bs].set(new)
    view = layer[block_tables]            # (B, max_blk, bs, H, hd)
    b, nb = block_tables.shape
    return layer, view.reshape(b, nb * bs, *layer.shape[2:])


def cache_logical_axes(cache):
    """Logical-axis pytree matching a decode cache, dense or paged.
    The heads axis sits at index 3 in BOTH layouts — dense K/V is
    (L, B, S, H, hd), the paged pool is (L, num_blocks, bs, H, hd) —
    so one annotation serves both, and under DECODE_RULES only that
    dim splits (over `tensor`).  pos/start/block_tables stay
    replicated: they are the host scheduler's view of the pool and
    must be readable without collectives."""
    axes = {"k": (None, None, None, "heads", "head_dim"),
            "v": (None, None, None, "heads", "head_dim"),
            "pos": (None,), "start": (None,)}
    if "block_tables" in cache:
        axes["block_tables"] = (None, None)
    return axes


def cache_shardings(cache, mesh, rules=None):
    """NamedSharding pytree for a cache on `mesh` (shape-guarded, so
    a KV-head count that doesn't divide the tensor degree replicates
    instead of erroring — llama nano GQA with one KV head)."""
    from ray_tpu.parallel.sharding import (DECODE_RULES,
                                           shardings_by_shape)
    return shardings_by_shape(cache, cache_logical_axes(cache), mesh,
                              rules if rules is not None
                              else DECODE_RULES)


def shard_cache(cache, mesh, rules=None):
    """Commit an existing cache's leaves to the mesh (device_put).
    Used when re-laying an already-populated cache; fresh caches
    should go through partitioned_cache_init instead so the full pool
    never materialises on one chip."""
    return jax.device_put(cache, cache_shardings(cache, mesh, rules))


def partitioned_cache_init(build_fn, mesh, rules=None):
    """Materialise a zeros cache directly in partitioned form:
    eval_shape the builder, derive guarded shardings, then jit it with
    out_shardings so each chip allocates only its own KV-pool shard.
    A 7B-class pool born this way never exists unsharded anywhere."""
    shapes = jax.eval_shape(build_fn)
    shardings = cache_shardings(shapes, mesh, rules)
    return jax.jit(build_fn, out_shardings=shardings)()


def dense_to_paged(cache, block_size: int):
    """Re-lay a dense cache into a fresh block pool (row-major block
    tables, block 0 reserved as the null block).  Pure reshape +
    concat — the pool holds byte-identical K/V, so paged decode
    continues a dense prefill exactly.  Used by generate_with's
    kv_layout="paged" path and the parity tests; the serve engine
    builds its pool through kv_pager instead."""
    k = cache["k"]
    L, B, S, *tail = k.shape
    if S % block_size:
        raise ValueError(f"max_seq={S} must be a multiple of "
                         f"block_size={block_size}")
    nb = S // block_size
    out = dict(cache)
    for name in ("k", "v"):
        pool = cache[name].reshape(L, B * nb, block_size, *tail)
        null = jnp.zeros((L, 1, block_size, *tail), pool.dtype)
        out[name] = jnp.concatenate([null, pool], axis=1)
    out["block_tables"] = (
        1 + jnp.arange(B * nb, dtype=jnp.int32).reshape(B, nb))
    return out


def copy_block(cache, src, dst):
    """Copy-on-write fork: duplicate pool block `src` into `dst` across
    every layer of both K and V, on device.  src/dst are dynamic int32
    scalars, so ONE jitted program serves every fork.  The pager calls
    this before a sequence writes into a block whose refcount > 1."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = dict(cache)
    for name in ("k", "v"):
        pool = cache[name]                 # (L, num_blocks, bs, ...)
        out[name] = pool.at[:, dst].set(pool[:, src])
    return out


def make_vocab_tail_mask(cfg) -> Optional[jnp.ndarray]:
    """Static (padded_vocab,) bool mask, True on the real vocab — built
    ONCE per generation (or jitted serve program) so sampling is a
    single jnp.where instead of rebuilding a fill tensor and scattering
    it over the tail on every sampled token.  None when nothing is
    padded."""
    if cfg.padded_vocab == cfg.vocab_size:
        return None
    return jnp.arange(cfg.padded_vocab) < cfg.vocab_size


def _mask_to_top_k(logits, top_k: int):
    """Keep only entries >= the k-th largest per row (last axis); ties
    at the threshold all survive.  Any leading batch dims."""
    kth = lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits >= kth, logits,
                     jnp.asarray(-1e30, logits.dtype))


def _mask_to_top_p(logits, top_p: float):
    """Nucleus filter over the last axis: keep the smallest
    descending-probability prefix whose mass reaches top_p.  A token
    is kept iff the mass STRICTLY BEFORE it is < top_p, so the top-1
    token always survives.  Works on logits already scaled by
    temperature (the nucleus is defined on the sampling
    distribution)."""
    order = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p
    inv = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    return jnp.where(keep, logits, jnp.asarray(-1e30, logits.dtype))


def filter_logits(logits, temperature: float,
                  tail_mask: Optional[jnp.ndarray],
                  top_k: int = 0, top_p: float = 1.0):
    """Temperature-scale then apply the static tail/top-k/top-p masks;
    returns the filtered f32-safe logits the categorical (or the
    spec-decode accept test) draws from.  temperature must be > 0."""
    if tail_mask is not None:
        logits = jnp.where(tail_mask, logits,
                           jnp.asarray(-1e30, logits.dtype))
    scaled = logits / jnp.float32(temperature)
    if top_k > 0:
        scaled = _mask_to_top_k(scaled, top_k)
    if top_p < 1.0:
        scaled = _mask_to_top_p(scaled, top_p)
    return scaled


def sample_token(logits, key, temperature: float,
                 tail_mask: Optional[jnp.ndarray],
                 top_k: int = 0, top_p: float = 1.0) -> jnp.ndarray:
    """(..., padded_vocab) logits → (...,) int32 token; the padded
    vocab tail can never be sampled.  temperature 0 = greedy (key and
    the filters are unused — argmax is filter-invariant).  top_k /
    top_p are jit-STATIC knobs (python ints/floats baked into the
    compiled program): top_k keeps the k most likely tokens, top_p
    keeps the smallest nucleus reaching that probability mass, both
    composed AFTER temperature scaling and with the tail mask
    preserved."""
    if temperature == 0.0:
        if tail_mask is not None:
            logits = jnp.where(tail_mask, logits,
                               jnp.asarray(-1e30, logits.dtype))
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = filter_logits(logits, temperature, tail_mask, top_k,
                           top_p)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


def spec_accept(logits, block, key, temperature: float,
                tail_mask: Optional[jnp.ndarray],
                top_k: int = 0, top_p: float = 1.0,
                draft_probs=None):
    """Speculative accept/reject over one verify round (round 11).

    block (B, T=k+1) int32 is [cur, d_1..d_k] — the last sampled token
    followed by the draft's k proposals; logits (B, T, padded_vocab)
    is the target model's verify forward over exactly those positions,
    so logits[:, t] is the target's distribution for the token AFTER
    block[:, t].  Returns (out_tokens (B, T) int32, n_acc (B,) int32);
    row b emitted out_tokens[b, :n_acc[b] + 1] — the accepted draft
    prefix plus one target-sampled correction/bonus token, so every
    round nets at least one token and the greedy path is bit-identical
    to sequential argmax decoding.

    temperature 0: accept d_{t+1} iff it equals argmax(logits[:, t])
    cumulatively (deterministic, key unused).  temperature > 0:
    standard rejection sampling — accept with prob min(1, p/q) where q
    is draft_probs (B, k, V), the draft's post-filter sampling
    distribution, or a one-hot on the proposal when the draft supplies
    no distribution (n-gram draft); the correction token comes from
    the normalised residual max(p - q, 0), which degenerates to p for
    the all-accepted bonus position (q is zero-padded there).
    """
    B, T = block.shape
    k = T - 1
    drafts = block[:, 1:]                                   # (B, k)
    cols = jnp.arange(T)
    if temperature == 0.0:
        if tail_mask is not None:
            logits = jnp.where(tail_mask, logits,
                               jnp.asarray(-1e30, logits.dtype))
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # (B, T)
        match = (drafts == g[:, :-1]).astype(jnp.int32)
        n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        corr = jnp.take_along_axis(g, n_acc[:, None], axis=1)
    else:
        filt = filter_logits(logits, temperature, tail_mask, top_k,
                             top_p)
        p = jax.nn.softmax(filt.astype(jnp.float32), axis=-1)
        V = p.shape[-1]
        if draft_probs is None:
            q = jax.nn.one_hot(drafts, V, dtype=p.dtype)
        else:
            q = draft_probs.astype(p.dtype)
        u_key, s_key = jax.random.split(key)
        u = jax.random.uniform(u_key, (B, k))
        idx = drafts[..., None]
        p_d = jnp.take_along_axis(p[:, :k], idx, axis=-1)[..., 0]
        q_d = jnp.take_along_axis(q, idx, axis=-1)[..., 0]
        ratio = p_d / jnp.maximum(q_d, 1e-20)
        accept = (u < jnp.minimum(1.0, ratio)).astype(jnp.int32)
        n_acc = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)
        q_pad = jnp.concatenate(
            [q, jnp.zeros((B, 1, V), q.dtype)], axis=1)
        sel = n_acc[:, None, None]
        p_at = jnp.take_along_axis(p, jnp.broadcast_to(sel, (B, 1, V)),
                                   axis=1)[:, 0]             # (B, V)
        q_at = jnp.take_along_axis(q_pad,
                                   jnp.broadcast_to(sel, (B, 1, V)),
                                   axis=1)[:, 0]
        residual = jnp.maximum(p_at - q_at, 0.0)
        mass = jnp.sum(residual, axis=-1, keepdims=True)
        residual = jnp.where(mass > 0, residual / mass, p_at)
        corr = jax.random.categorical(
            s_key, jnp.log(residual + 1e-30))[:, None]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), drafts.dtype)], axis=1)
    out = jnp.where(cols[None, :] < n_acc[:, None], drafts_pad,
                    corr.astype(drafts_pad.dtype))
    return out.astype(jnp.int32), n_acc.astype(jnp.int32)


def make_spec_verify(verify_step_fn, cfg, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 1.0):
    """Compose a family's verify_step with spec_accept into the
    canonical spec-decode verify program: ONE target dispatch checks a
    whole draft block and advances pos by the tokens actually kept.

    Returned greedy signature: (params, cache, block, key) →
    (out_tokens, n_acc, cache); sampled adds a trailing draft_probs
    arg.  The cache's pos lands at old_pos + n_acc + 1 — the next
    write slot after the last EMITTED token's K/V (the correction
    token itself has no K/V yet, exactly like a freshly sampled token
    in the plain decode step).  K/V written for rejected draft
    positions sits at slots >= the new pos: never attendable under
    slot_mask, overwritten by later rounds — the dense rollback IS the
    pos rewind.  Paged caches need no block surgery either: every
    row's blocks are reserved for the full request at admission, so
    rejected writes land in row-private blocks (or the null block past
    max_seq) that the row still owns."""
    tail = make_vocab_tail_mask(cfg)
    if temperature == 0.0:
        def spec_verify(params, cache, block, key):
            logits, cache = verify_step_fn(params, cache, block, cfg)
            out, n_acc = spec_accept(logits, block, key, 0.0, tail)
            cache = dict(cache)
            cache["pos"] = cache["pos"] + n_acc + 1
            return out, n_acc, cache
        return spec_verify

    def spec_verify(params, cache, block, key, draft_probs=None):
        logits, cache = verify_step_fn(params, cache, block, cfg)
        out, n_acc = spec_accept(logits, block, key, temperature,
                                 tail, top_k, top_p, draft_probs)
        cache = dict(cache)
        cache["pos"] = cache["pos"] + n_acc + 1
        return out, n_acc, cache
    return spec_verify


def spec_rewind(cache, n_rejected):
    """Roll a cache back over rejected draft positions: pure per-row
    pos arithmetic (n_rejected (B,) int32).  The stale K/V needs no
    scrubbing — slot_mask derives attendability from pos, so rewound
    slots are invisible until overwritten."""
    out = dict(cache)
    out["pos"] = cache["pos"] - jnp.asarray(n_rejected, jnp.int32)
    return out


def make_draft_propose(decode_step_fn, cfg, k: int,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0, with_probs: bool = False):
    """Build the jitted draft-side program for model-draft spec
    decode: rewind the draft cache over last round's rejections, then
    run k+1 chained draft decode steps in a scan — feeding
    [cur, d_1..d_k] so the final step writes d_k's K/V, which makes
    the rewind arithmetic uniform (the draft cache always holds K/V
    for every fed token; pos nets +n_acc+1 per round, mirroring the
    target).

    Returned signature: (params, cache, cur (B,), n_rejected (B,),
    key) → (drafts (B, k), cache) — or (drafts, probs (B, k, V),
    cache) when with_probs (the post-filter distribution each d_t was
    sampled from, required by sampled-mode spec_accept)."""
    if with_probs and temperature == 0.0:
        raise ValueError("with_probs requires temperature > 0 (greedy "
                         "spec_accept never consults draft_probs)")
    tail = make_vocab_tail_mask(cfg)

    def draft_propose(params, cache, cur, n_rejected, key):
        cache = spec_rewind(cache, n_rejected)

        def body(carry, kk):
            cache, tok = carry
            logits, cache = decode_step_fn(params, cache, tok, cfg)
            if temperature == 0.0:
                nxt = sample_token(logits, kk, 0.0, tail)
                probs = jnp.zeros((), jnp.float32)      # unused
            else:
                filt = filter_logits(logits, temperature, tail,
                                     top_k, top_p)
                probs = jax.nn.softmax(filt.astype(jnp.float32),
                                       axis=-1)
                nxt = jax.random.categorical(kk, filt).astype(
                    jnp.int32)
            return (cache, nxt), (nxt, probs)

        keys = jax.random.split(key, k)
        (cache, last), (drafts, probs) = lax.scan(
            body, (cache, cur), keys)
        # Extra (k+1)-th step: ingest d_k's K/V, logits discarded.
        _, cache = decode_step_fn(params, cache, last, cfg)
        drafts = drafts.T                               # (B, k)
        if with_probs:
            return drafts, jnp.swapaxes(probs, 0, 1), cache
        return drafts, cache
    return draft_propose


def ngram_propose(tokens, k: int, order: int = 2):
    """Host-side zero-weight draft: propose the k tokens that followed
    the most recent previous occurrence of the current trailing
    `order`-gram in this request's own history (prompt + emitted).
    Falls back to repeating the last token when no prior occurrence
    (or history shorter than the gram) exists — proposal quality only
    moves the acceptance rate, never correctness, because every
    proposal is target-verified."""
    toks = list(tokens)
    n = len(toks)
    fallback = [toks[-1]] * k if toks else [0] * k
    if n <= order:
        return fallback
    gram = toks[n - order:]
    for i in range(n - order - 1, -1, -1):
        if toks[i:i + order] == gram:
            cont = toks[i + order:i + order + k]
            if cont:
                return (cont + [cont[-1]] * (k - len(cont)))[:k]
            break
    return fallback


def scan_prefill(init_cache_fn, decode_step_fn, params, prompt, cfg):
    """Per-token reference prefill: T0 sequential decode_step dispatches
    (the pre-round-7 path).  Kept as the numerics oracle for the
    batched prefill parity tests; equal-length prompts only.  Returns
    (last_logits (B, padded_vocab), cache)."""
    B = prompt.shape[0]
    cache = init_cache_fn(cfg, B)

    def prefill_step(cache, tok):
        logits, cache = decode_step_fn(params, cache, tok, cfg)
        return cache, logits

    cache, logits_seq = lax.scan(prefill_step, cache, prompt.T)
    return logits_seq[-1], cache


def generate_with(prefill_fn, decode_step_fn, params,
                  prompt: jnp.ndarray, cfg, *, max_new_tokens: int,
                  lengths: Optional[jnp.ndarray] = None,
                  temperature: float = 1.0,
                  top_k: int = 0, top_p: float = 1.0,
                  key: Optional[jax.Array] = None,
                  kv_layout: str = "dense",
                  kv_block_size: int = 16) -> jnp.ndarray:
    """The generation loop shared by every decoder family (gpt2,
    llama): ONE batched prefill dispatch + a sampling scan over the
    family's decode_step.  prompt (B, T0) int32 → (B, T0 +
    max_new_tokens) int32; `lengths` (B,) marks ragged LEFT-padded
    prompts (row b's real tokens occupy columns [T0 - lengths[b], T0));
    temperature 0 = greedy; top_k/top_p are jit-static sampling
    filters (see sample_token); the whole program jits (static cfg /
    max_new_tokens).  kv_layout="paged" re-lays the prefilled cache
    into kv_block_size blocks and decodes through the block-table
    gather/scatter path — the dense layout is its parity oracle."""
    B, T0 = prompt.shape
    if kv_layout not in ("dense", "paged"):
        raise ValueError(f"kv_layout must be 'dense' or 'paged', got "
                         f"{kv_layout!r}")
    if T0 + max_new_tokens > cfg.max_seq:
        # Past max_seq JAX clamps dynamic_update_slice/gather indices, so
        # KV writes would silently pile onto the last cache slot (and
        # position lookups would saturate) — error loudly instead.
        raise ValueError(
            f"prompt length {T0} + max_new_tokens {max_new_tokens} "
            f"exceeds cfg.max_seq={cfg.max_seq}")
    if key is None:
        key = jax.random.PRNGKey(0)
    tail_mask = make_vocab_tail_mask(cfg)
    last_logits, cache = prefill_fn(params, prompt, cfg,
                                    lengths=lengths)
    if kv_layout == "paged":
        cache = dense_to_paged(cache, kv_block_size)

    def gen_step(carry, k):
        cache, logits = carry
        tok = sample_token(logits, k, temperature, tail_mask,
                           top_k, top_p)
        new_logits, cache = decode_step_fn(params, cache, tok, cfg)
        return (cache, new_logits), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _), new_tokens = lax.scan(gen_step, (cache, last_logits), keys)
    return jnp.concatenate([prompt, new_tokens.T.astype(prompt.dtype)],
                           axis=1)
