"""The generation machinery shared by every decoder family.

Family modules (gpt2_decode, llama_decode) supply their
(init_cache_fn, prefill_fn, decode_step_fn) triple; this module owns
the family-neutral prefill dispatch + sampling scan so fixes land once.

Cache contract (vector positions, round 7 — ragged batches decode
together):

  k, v  : (L, B, S, ...) preallocated at cfg.max_seq
  pos   : (B,) int32 — next cache slot each sequence writes
  start : (B,) int32 — first valid slot (the left-pad offset); the
          LOGICAL position of the token at slot s is s - start[b], so
          the next token's wpe/RoPE index is pos[b] - start[b]

The per-slot attention mask is derived, not stored: slot s is
attendable for row b iff start[b] <= s <= pos[b] (after the current
token's K/V lands at slot pos[b]).  Equal-length prompts are the
degenerate case start == 0.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def slot_mask(start: jnp.ndarray, end: jnp.ndarray,
              max_seq: int) -> jnp.ndarray:
    """(B, S) bool — cache slots holding attendable K/V per row:
    start[b] <= s < end[b] (end exclusive)."""
    s = jnp.arange(max_seq)
    return (s[None, :] >= start[:, None]) & (s[None, :] < end[:, None])


def make_vocab_tail_mask(cfg) -> Optional[jnp.ndarray]:
    """Static (padded_vocab,) bool mask, True on the real vocab — built
    ONCE per generation (or jitted serve program) so sampling is a
    single jnp.where instead of rebuilding a fill tensor and scattering
    it over the tail on every sampled token.  None when nothing is
    padded."""
    if cfg.padded_vocab == cfg.vocab_size:
        return None
    return jnp.arange(cfg.padded_vocab) < cfg.vocab_size


def sample_token(logits, key, temperature: float,
                 tail_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """(B, padded_vocab) logits → (B,) int32 token; the padded vocab
    tail can never be sampled.  temperature 0 = greedy (key unused)."""
    if tail_mask is not None:
        logits = jnp.where(tail_mask, logits,
                           jnp.asarray(-1e30, logits.dtype))
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logits / jnp.float32(temperature)).astype(jnp.int32)


def scan_prefill(init_cache_fn, decode_step_fn, params, prompt, cfg):
    """Per-token reference prefill: T0 sequential decode_step dispatches
    (the pre-round-7 path).  Kept as the numerics oracle for the
    batched prefill parity tests; equal-length prompts only.  Returns
    (last_logits (B, padded_vocab), cache)."""
    B = prompt.shape[0]
    cache = init_cache_fn(cfg, B)

    def prefill_step(cache, tok):
        logits, cache = decode_step_fn(params, cache, tok, cfg)
        return cache, logits

    cache, logits_seq = lax.scan(prefill_step, cache, prompt.T)
    return logits_seq[-1], cache


def generate_with(prefill_fn, decode_step_fn, params,
                  prompt: jnp.ndarray, cfg, *, max_new_tokens: int,
                  lengths: Optional[jnp.ndarray] = None,
                  temperature: float = 1.0,
                  key: Optional[jax.Array] = None) -> jnp.ndarray:
    """The generation loop shared by every decoder family (gpt2,
    llama): ONE batched prefill dispatch + a sampling scan over the
    family's decode_step.  prompt (B, T0) int32 → (B, T0 +
    max_new_tokens) int32; `lengths` (B,) marks ragged LEFT-padded
    prompts (row b's real tokens occupy columns [T0 - lengths[b], T0));
    temperature 0 = greedy; the whole program jits (static cfg /
    max_new_tokens)."""
    B, T0 = prompt.shape
    if T0 + max_new_tokens > cfg.max_seq:
        # Past max_seq JAX clamps dynamic_update_slice/gather indices, so
        # KV writes would silently pile onto the last cache slot (and
        # position lookups would saturate) — error loudly instead.
        raise ValueError(
            f"prompt length {T0} + max_new_tokens {max_new_tokens} "
            f"exceeds cfg.max_seq={cfg.max_seq}")
    if key is None:
        key = jax.random.PRNGKey(0)
    tail_mask = make_vocab_tail_mask(cfg)
    last_logits, cache = prefill_fn(params, prompt, cfg,
                                    lengths=lengths)

    def gen_step(carry, k):
        cache, logits = carry
        tok = sample_token(logits, k, temperature, tail_mask)
        new_logits, cache = decode_step_fn(params, cache, tok, cfg)
        return (cache, new_logits), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _), new_tokens = lax.scan(gen_step, (cache, last_logits), keys)
    return jnp.concatenate([prompt, new_tokens.T.astype(prompt.dtype)],
                           axis=1)
