"""The generation machinery shared by every decoder family.

Family modules (gpt2_decode, llama_decode) supply their
(init_cache_fn, prefill_fn, decode_step_fn) triple; this module owns
the family-neutral prefill dispatch + sampling scan so fixes land once.

Cache contract (vector positions, round 7 — ragged batches decode
together):

  k, v  : (L, B, S, ...) preallocated at cfg.max_seq
  pos   : (B,) int32 — next cache slot each sequence writes
  start : (B,) int32 — first valid slot (the left-pad offset); the
          LOGICAL position of the token at slot s is s - start[b], so
          the next token's wpe/RoPE index is pos[b] - start[b]

The per-slot attention mask is derived, not stored: slot s is
attendable for row b iff start[b] <= s <= pos[b] (after the current
token's K/V lands at slot pos[b]).  Equal-length prompts are the
degenerate case start == 0.

Paged layout (round 8 — block-paged KV with prefix reuse): instead of
dense per-row (L, B, S, ...) cache tensors, K/V live in a shared pool
of fixed-size blocks

  k, v          : (L, num_blocks, block_size, ...) preallocated pool
  block_tables  : (B, S // block_size) int32 — row b's j-th table entry
                  names the pool block holding slots
                  [j*block_size, (j+1)*block_size); block 0 is the
                  reserved null/trash block (never allocated, absorbs
                  masked pad writes)
  pos, start    : unchanged

The jitted decode step reads the cache through a gather by block id
(one layer at a time inside the layer scan — never the whole dense
cache at once) and writes the new token with a scatter at
(block_tables[b, pos//bs], pos % bs).  Because table entries are kept
in sequence order, the gathered view is value-identical to the dense
layout, so attention numerics are bit-identical between layouts — the
dense path stays the parity oracle (same pattern as
prefill_impl="scan").  Host-side block allocation / refcounting /
prefix hashing lives in ray_tpu/serve/kv_pager.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def slot_mask(start: jnp.ndarray, end: jnp.ndarray,
              max_seq: int) -> jnp.ndarray:
    """(B, S) bool — cache slots holding attendable K/V per row:
    start[b] <= s < end[b] (end exclusive)."""
    s = jnp.arange(max_seq)
    return (s[None, :] >= start[:, None]) & (s[None, :] < end[:, None])


def is_paged(cache) -> bool:
    """The cache pytree itself is the layout knob: a pool cache carries
    a block table, a dense cache doesn't.  Static under jit (pytree
    structure), so the python branch costs nothing."""
    return "block_tables" in cache


def paged_update_and_view(layer, block_tables, pos, new):
    """One decode-step K (or V) update against a paged pool layer.

    layer (num_blocks, bs, H, hd) is one layer's block pool;
    block_tables (B, max_blk) int32; pos (B,) int32; new (B, H, hd).
    Writes new[b] into block block_tables[b, pos[b]//bs] at offset
    pos[b] % bs (every active row's tail block is private, so the
    scatter is conflict-free), then gathers each row's blocks into the
    dense-equivalent (B, max_blk*bs, H, hd) attention view.  Table
    entries are sequence-ordered, so view[b, s] holds exactly what the
    dense cache would hold at slot s — unattended slots carry other
    sequences' bytes, but the slot mask replaces them with the same
    -1e30 the dense path writes, keeping logits bit-identical."""
    bs = layer.shape[1]
    rows = jnp.arange(block_tables.shape[0])
    blk = block_tables[rows, pos // bs]
    layer = layer.at[blk, pos % bs].set(new)
    view = layer[block_tables]            # (B, max_blk, bs, H, hd)
    b, nb = block_tables.shape
    return layer, view.reshape(b, nb * bs, *layer.shape[2:])


def cache_logical_axes(cache):
    """Logical-axis pytree matching a decode cache, dense or paged.
    The heads axis sits at index 3 in BOTH layouts — dense K/V is
    (L, B, S, H, hd), the paged pool is (L, num_blocks, bs, H, hd) —
    so one annotation serves both, and under DECODE_RULES only that
    dim splits (over `tensor`).  pos/start/block_tables stay
    replicated: they are the host scheduler's view of the pool and
    must be readable without collectives."""
    axes = {"k": (None, None, None, "heads", "head_dim"),
            "v": (None, None, None, "heads", "head_dim"),
            "pos": (None,), "start": (None,)}
    if "block_tables" in cache:
        axes["block_tables"] = (None, None)
    return axes


def cache_shardings(cache, mesh, rules=None):
    """NamedSharding pytree for a cache on `mesh` (shape-guarded, so
    a KV-head count that doesn't divide the tensor degree replicates
    instead of erroring — llama nano GQA with one KV head)."""
    from ray_tpu.parallel.sharding import (DECODE_RULES,
                                           shardings_by_shape)
    return shardings_by_shape(cache, cache_logical_axes(cache), mesh,
                              rules if rules is not None
                              else DECODE_RULES)


def shard_cache(cache, mesh, rules=None):
    """Commit an existing cache's leaves to the mesh (device_put).
    Used when re-laying an already-populated cache; fresh caches
    should go through partitioned_cache_init instead so the full pool
    never materialises on one chip."""
    return jax.device_put(cache, cache_shardings(cache, mesh, rules))


def partitioned_cache_init(build_fn, mesh, rules=None):
    """Materialise a zeros cache directly in partitioned form:
    eval_shape the builder, derive guarded shardings, then jit it with
    out_shardings so each chip allocates only its own KV-pool shard.
    A 7B-class pool born this way never exists unsharded anywhere."""
    shapes = jax.eval_shape(build_fn)
    shardings = cache_shardings(shapes, mesh, rules)
    return jax.jit(build_fn, out_shardings=shardings)()


def dense_to_paged(cache, block_size: int):
    """Re-lay a dense cache into a fresh block pool (row-major block
    tables, block 0 reserved as the null block).  Pure reshape +
    concat — the pool holds byte-identical K/V, so paged decode
    continues a dense prefill exactly.  Used by generate_with's
    kv_layout="paged" path and the parity tests; the serve engine
    builds its pool through kv_pager instead."""
    k = cache["k"]
    L, B, S, *tail = k.shape
    if S % block_size:
        raise ValueError(f"max_seq={S} must be a multiple of "
                         f"block_size={block_size}")
    nb = S // block_size
    out = dict(cache)
    for name in ("k", "v"):
        pool = cache[name].reshape(L, B * nb, block_size, *tail)
        null = jnp.zeros((L, 1, block_size, *tail), pool.dtype)
        out[name] = jnp.concatenate([null, pool], axis=1)
    out["block_tables"] = (
        1 + jnp.arange(B * nb, dtype=jnp.int32).reshape(B, nb))
    return out


def copy_block(cache, src, dst):
    """Copy-on-write fork: duplicate pool block `src` into `dst` across
    every layer of both K and V, on device.  src/dst are dynamic int32
    scalars, so ONE jitted program serves every fork.  The pager calls
    this before a sequence writes into a block whose refcount > 1."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = dict(cache)
    for name in ("k", "v"):
        pool = cache[name]                 # (L, num_blocks, bs, ...)
        out[name] = pool.at[:, dst].set(pool[:, src])
    return out


def make_vocab_tail_mask(cfg) -> Optional[jnp.ndarray]:
    """Static (padded_vocab,) bool mask, True on the real vocab — built
    ONCE per generation (or jitted serve program) so sampling is a
    single jnp.where instead of rebuilding a fill tensor and scattering
    it over the tail on every sampled token.  None when nothing is
    padded."""
    if cfg.padded_vocab == cfg.vocab_size:
        return None
    return jnp.arange(cfg.padded_vocab) < cfg.vocab_size


def sample_token(logits, key, temperature: float,
                 tail_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """(B, padded_vocab) logits → (B,) int32 token; the padded vocab
    tail can never be sampled.  temperature 0 = greedy (key unused)."""
    if tail_mask is not None:
        logits = jnp.where(tail_mask, logits,
                           jnp.asarray(-1e30, logits.dtype))
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logits / jnp.float32(temperature)).astype(jnp.int32)


def scan_prefill(init_cache_fn, decode_step_fn, params, prompt, cfg):
    """Per-token reference prefill: T0 sequential decode_step dispatches
    (the pre-round-7 path).  Kept as the numerics oracle for the
    batched prefill parity tests; equal-length prompts only.  Returns
    (last_logits (B, padded_vocab), cache)."""
    B = prompt.shape[0]
    cache = init_cache_fn(cfg, B)

    def prefill_step(cache, tok):
        logits, cache = decode_step_fn(params, cache, tok, cfg)
        return cache, logits

    cache, logits_seq = lax.scan(prefill_step, cache, prompt.T)
    return logits_seq[-1], cache


def generate_with(prefill_fn, decode_step_fn, params,
                  prompt: jnp.ndarray, cfg, *, max_new_tokens: int,
                  lengths: Optional[jnp.ndarray] = None,
                  temperature: float = 1.0,
                  key: Optional[jax.Array] = None,
                  kv_layout: str = "dense",
                  kv_block_size: int = 16) -> jnp.ndarray:
    """The generation loop shared by every decoder family (gpt2,
    llama): ONE batched prefill dispatch + a sampling scan over the
    family's decode_step.  prompt (B, T0) int32 → (B, T0 +
    max_new_tokens) int32; `lengths` (B,) marks ragged LEFT-padded
    prompts (row b's real tokens occupy columns [T0 - lengths[b], T0));
    temperature 0 = greedy; the whole program jits (static cfg /
    max_new_tokens).  kv_layout="paged" re-lays the prefilled cache
    into kv_block_size blocks and decodes through the block-table
    gather/scatter path — the dense layout is its parity oracle."""
    B, T0 = prompt.shape
    if kv_layout not in ("dense", "paged"):
        raise ValueError(f"kv_layout must be 'dense' or 'paged', got "
                         f"{kv_layout!r}")
    if T0 + max_new_tokens > cfg.max_seq:
        # Past max_seq JAX clamps dynamic_update_slice/gather indices, so
        # KV writes would silently pile onto the last cache slot (and
        # position lookups would saturate) — error loudly instead.
        raise ValueError(
            f"prompt length {T0} + max_new_tokens {max_new_tokens} "
            f"exceeds cfg.max_seq={cfg.max_seq}")
    if key is None:
        key = jax.random.PRNGKey(0)
    tail_mask = make_vocab_tail_mask(cfg)
    last_logits, cache = prefill_fn(params, prompt, cfg,
                                    lengths=lengths)
    if kv_layout == "paged":
        cache = dense_to_paged(cache, kv_block_size)

    def gen_step(carry, k):
        cache, logits = carry
        tok = sample_token(logits, k, temperature, tail_mask)
        new_logits, cache = decode_step_fn(params, cache, tok, cfg)
        return (cache, new_logits), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _), new_tokens = lax.scan(gen_step, (cache, last_logits), keys)
    return jnp.concatenate([prompt, new_tokens.T.astype(prompt.dtype)],
                           axis=1)
