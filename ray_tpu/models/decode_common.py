"""The generation loop shared by every decoder family.

Family modules (gpt2_decode, llama_decode) supply their
(init_cache_fn, decode_step_fn) pair; this module owns the
family-neutral prefill + sampling scans so fixes land once.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def generate_with(init_cache_fn, decode_step_fn, params,
                  prompt: jnp.ndarray, cfg, *, max_new_tokens: int,
                  temperature: float = 1.0,
                  key: Optional[jax.Array] = None) -> jnp.ndarray:
    """The generation loop shared by every decoder family (gpt2,
    llama): prefill scan + sampling scan over the family's
    (init_cache_fn, decode_step_fn) pair.  prompt (B, T0) int32 →
    (B, T0 + max_new_tokens) int32; temperature 0 = greedy; the whole
    program jits (static cfg / max_new_tokens)."""
    B, T0 = prompt.shape
    if T0 + max_new_tokens > cfg.max_seq:
        # Past max_seq JAX clamps dynamic_update_slice/gather indices, so
        # KV writes would silently pile onto the last cache slot (and
        # position lookups would saturate) — error loudly instead.
        raise ValueError(
            f"prompt length {T0} + max_new_tokens {max_new_tokens} "
            f"exceeds cfg.max_seq={cfg.max_seq}")
    if key is None:
        key = jax.random.PRNGKey(0)
    cache = init_cache_fn(cfg, B)

    def prefill_step(cache, tok):
        logits, cache = decode_step_fn(params, cache, tok, cfg)
        return cache, logits

    cache, logits_seq = lax.scan(prefill_step, cache, prompt.T)
    last_logits = logits_seq[-1]                         # (B, V)

    def sample(logits, k):
        # mask the padded vocab tail so it can never be sampled
        if cfg.padded_vocab != cfg.vocab_size:
            neg = jnp.full((cfg.padded_vocab - cfg.vocab_size,), -1e30,
                           dtype=logits.dtype)
            logits = logits.at[..., cfg.vocab_size:].set(neg)
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            k, logits / jnp.float32(temperature)).astype(jnp.int32)

    def gen_step(carry, k):
        cache, logits = carry
        tok = sample(logits, k)
        new_logits, cache = decode_step_fn(params, cache, tok, cfg)
        return (cache, new_logits), tok

    keys = jax.random.split(key, max_new_tokens)
    (_, _), new_tokens = lax.scan(gen_step, (cache, last_logits), keys)
    return jnp.concatenate([prompt, new_tokens.T.astype(prompt.dtype)],
                           axis=1)


