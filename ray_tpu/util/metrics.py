"""Application-defined metrics: Counter / Gauge / Histogram.

Role-equivalent of the reference's custom-metrics API
(``python/ray/util/metrics.py`` over the Cython metric shim and the
per-node OpenCensus→Prometheus agent, ``_private/metrics_agent.py:93``).
Collapsed TPU-build design: each process keeps a local registry and a
background publisher flushes snapshots into GCS KV
(``metrics:<worker_id>``); the dashboard's ``/metrics`` endpoint merges
every live snapshot into one Prometheus text page.
"""

from __future__ import annotations

import re
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

_KV_PREFIX = "metrics:"
_PUBLISH_INTERVAL_S = 5.0

#: Prometheus-safe metric names (the repo-wide guard test holds every
#: Counter/Gauge/Histogram under ray_tpu/ to the same pattern)
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


class _Registry:
    def __init__(self):
        self.metrics: Dict[str, "Metric"] = {}
        self._lock = threading.Lock()
        self._publisher: Optional[threading.Thread] = None
        self._dup_warned: set = set()

    def register(self, metric: "Metric") -> None:
        with self._lock:
            old = self.metrics.get(metric.name)
            if (old is not None and old is not metric
                    and metric.name not in self._dup_warned):
                # warn ONCE per name instead of silently overwriting:
                # two live instances under one name means one of them
                # publishes and the other's observations vanish
                self._dup_warned.add(metric.name)
                warnings.warn(
                    f"metric {metric.name!r} registered more than once "
                    f"in this process; the newest instance replaces the "
                    f"previous one in the registry (share one instance "
                    f"instead)", RuntimeWarning, stacklevel=4)
            self.metrics[metric.name] = metric
        self._ensure_publisher()

    def _ensure_publisher(self) -> None:
        with self._lock:
            if self._publisher is not None:
                return
            self._publisher = threading.Thread(
                target=self._publish_loop, daemon=True,
                name="raytpu-metrics")
            self._publisher.start()

    def snapshot(self) -> dict:
        with self._lock:
            return {name: m._dump() for name, m in self.metrics.items()}

    def _publish_loop(self) -> None:
        from ray_tpu._private import worker_context

        while True:
            time.sleep(_PUBLISH_INTERVAL_S)
            cw = worker_context.maybe_core_worker()
            if cw is None:
                continue
            try:
                import msgpack

                cw.kv_put(
                    _KV_PREFIX + cw.worker_id.hex(),
                    msgpack.packb({"ts": time.time(),
                                   "metrics": self.snapshot()}))
            except Exception:  # noqa: BLE001 - shutdown race
                pass


_registry = _Registry()


class Metric:
    """Base: name, help text, tag keys; values tracked per tag-tuple."""

    kind = "gauge"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"invalid metric name {name!r}: must match "
                f"^[a-z][a-z0-9_]*$ (Prometheus-exportable)")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _dump(self) -> dict:
        with self._lock:
            return {"kind": self.kind, "desc": self.description,
                    "values": [(list(k), v)
                               for k, v in self._values.items()]}


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    """Fixed-boundary histogram (values stored as per-bucket counters +
    sum/count, Prometheus-style)."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (), tag_keys: Sequence[str] = ()):
        if not boundaries:
            raise ValueError("histogram needs bucket boundaries")
        self.boundaries = sorted(float(b) for b in boundaries)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        base = self._key(tags)
        with self._lock:
            # Prometheus histograms are CUMULATIVE: an observation
            # increments every bucket whose bound >= value, plus +Inf.
            for b in self.boundaries:
                if value <= b:
                    k = base + (("le", str(b)),)
                    self._values[k] = self._values.get(k, 0.0) + 1
            k = base + (("le", "+Inf"),)
            self._values[k] = self._values.get(k, 0.0) + 1
            s = base + (("_stat", "sum"),)
            c = base + (("_stat", "count"),)
            self._values[s] = self._values.get(s, 0.0) + value
            self._values[c] = self._values.get(c, 0.0) + 1

    def _dump(self) -> dict:
        # Emit EVERY configured boundary (zero-filled) plus +Inf and
        # sum/count per tag-set: observe() only touches buckets whose
        # bound >= value, so a raw dump omits the low zero-count
        # buckets and Prometheus histogram_quantile then works on an
        # incomplete cumulative series.  A never-observed histogram
        # still emits one all-zero series under its default tags so the
        # full bucket layout is visible from registration time.
        with self._lock:
            bases = {tuple(t for t in k
                           if t[0] not in ("le", "_stat"))
                     for k in self._values}
            if not bases:
                bases = {self._key(None)}
            values = []
            for base in sorted(bases):
                for b in self.boundaries:
                    k = base + (("le", str(b)),)
                    values.append((list(k), self._values.get(k, 0.0)))
                for suffix in (("le", "+Inf"), ("_stat", "sum"),
                               ("_stat", "count")):
                    k = base + (suffix,)
                    values.append((list(k), self._values.get(k, 0.0)))
            return {"kind": self.kind, "desc": self.description,
                    "boundaries": list(self.boundaries),
                    "values": values}


def collect_cluster_metrics(kv_get, kv_keys, max_age_s: float = 60.0
                            ) -> List[str]:
    """Merge every process's published snapshot into Prometheus text
    lines (used by the dashboard /metrics endpoint)."""
    import msgpack

    lines: List[str] = []
    seen_help: set = set()
    now = time.time()
    for key in kv_keys(_KV_PREFIX):
        raw = kv_get(key)
        if not raw:
            continue
        try:
            snap = msgpack.unpackb(raw, raw=False)
        except Exception:  # noqa: BLE001
            continue
        if now - snap.get("ts", 0) > max_age_s:
            continue
        wid = key[len(_KV_PREFIX):][:12]
        for name, m in snap.get("metrics", {}).items():
            full = f"raytpu_app_{name}"
            kind = m["kind"]
            if full not in seen_help:
                seen_help.add(full)
                ptype = {"counter": "counter",
                         "histogram": "histogram"}.get(kind, "gauge")
                lines.append(f"# HELP {full} {m.get('desc', '')}")
                lines.append(f"# TYPE {full} {ptype}")
            for tag_list, value in m.get("values", []):
                tags = dict(tag_list)
                stat = tags.pop("_stat", None)
                series = full
                if kind == "histogram":
                    # Prometheus exposition: <name>_bucket{le=...},
                    # <name>_sum, <name>_count.
                    if stat == "sum":
                        series = full + "_sum"
                    elif stat == "count":
                        series = full + "_count"
                    elif "le" in tags:
                        series = full + "_bucket"
                label_str = ",".join(
                    [f'worker="{wid}"'] +
                    [f'{k}="{v}"' for k, v in sorted(tags.items())])
                lines.append(f"{series}{{{label_str}}} {value}")
    return lines
