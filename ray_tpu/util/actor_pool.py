"""ActorPool (reference analog: python/ray/util/actor_pool.py): schedule
a stream of method calls over a fixed set of actors."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List):
        self._idle = list(actors)
        self._pending_owner = {}
        self._result_slots = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable, value) -> None:
        """fn(actor, value) -> ObjectRef; blocks if no actor is idle."""
        while not self._idle:
            before = len(self._idle)
            self._wait_one()
            if len(self._idle) == before:
                raise TimeoutError(
                    "ActorPool.submit: no actor became idle within the "
                    "wait timeout; all actors still have pending tasks")
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._pending_owner[ref] = actor
        self._result_slots[self._next_task_index] = ref
        self._next_task_index += 1

    def _wait_one(self) -> None:
        refs = list(self._pending_owner)
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=300)
        for ref in ready:
            self._idle.append(self._pending_owner[ref])
            del self._pending_owner[ref]

    def get_next(self, timeout: float = 300.0):
        """Next result in submission order."""
        idx = self._next_return_index
        if idx not in self._result_slots:
            raise StopIteration("no pending results")
        ref = self._result_slots.pop(idx)
        self._next_return_index += 1
        value = ray_tpu.get(ref, timeout=timeout)
        actor = self._pending_owner.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)
        return value

    def get_next_unordered(self, timeout: float = 300.0):
        refs = [r for r in self._result_slots.values()
                if r in self._pending_owner] or \
            list(self._result_slots.values())
        if not refs:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        ref = ready[0]
        for idx, r in list(self._result_slots.items()):
            if r == ref:
                del self._result_slots[idx]
        actor = self._pending_owner.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)
        return ray_tpu.get(ref, timeout=timeout)

    def map(self, fn: Callable, values: Iterable) -> Iterable:
        values = list(values)
        for v in values:
            self.submit(fn, v)
        for _ in values:
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterable:
        values = list(values)
        for v in values:
            self.submit(fn, v)
        for _ in values:
            yield self.get_next_unordered()

    def has_next(self) -> bool:
        return bool(self._result_slots)