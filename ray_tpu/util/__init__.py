"""Utility APIs (reference analog: python/ray/util/)."""

from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)

__all__ = ["PlacementGroup", "placement_group", "remove_placement_group",
           "PlacementGroupSchedulingStrategy",
           "NodeAffinitySchedulingStrategy",
           # submodules with import-time side effects stay lazy:
           # ray_tpu.util.metrics, .iter, .tracing, .joblib_backend,
           # .dask_scheduler, .actor_pool, .queue, .multiprocessing,
           # .state
           ]
