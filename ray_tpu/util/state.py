"""State/observability API (reference analog:
python/ray/experimental/state/api.py + dashboard/state_aggregator.py:132
StateAPIManager — `ray list actors/tasks/...`, summaries)."""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker_context


def _gcs_call(method: str, payload: Optional[dict] = None):
    import ray_tpu

    ray_tpu._auto_init()
    cw = worker_context.core_worker()
    return cw.io.run(cw.gcs.call(method, payload or {}))


def list_nodes() -> List[Dict[str, Any]]:
    return [{"node_id": n["node_id"].hex(), "alive": n["alive"],
             "address": n["address"], "resources": n["resources_total"],
             "available": n.get("resources_available", {})}
            for n in _gcs_call("node_list")]


def list_actors() -> List[Dict[str, Any]]:
    return [{"actor_id": a["actor_id"].hex(), "name": a["name"],
             "state": a["state"],
             "node_id": a["node_id"].hex() if a.get("node_id") else "",
             "num_restarts": a.get("num_restarts", 0),
             "resources": a.get("resources", {})}
            for a in _gcs_call("actor_list")]


def list_tasks(limit: int = 10000) -> List[Dict[str, Any]]:
    """Finished-task events (start/end/worker); running tasks appear once
    their worker flushes (~1s)."""
    return _gcs_call("task_events_list", {"limit": limit})


def list_placement_groups() -> List[Dict[str, Any]]:
    return [{"pg_id": p["pg_id"].hex(), "name": p["name"],
             "state": p["state"], "strategy": p["strategy"],
             "bundles": p["bundles"]}
            for p in _gcs_call("pg_list")]


def summarize_tasks() -> Dict[str, Any]:
    events = list_tasks()
    by_name = Counter(e["name"] for e in events)
    total_s = sum(e["end"] - e["start"] for e in events)
    return {"total": len(events), "by_func_name": dict(by_name),
            "total_execution_s": round(total_s, 3)}


def summarize_actors() -> Dict[str, Any]:
    actors = list_actors()
    return {"total": len(actors),
            "by_state": dict(Counter(a["state"] for a in actors))}


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace events for chrome://tracing / Perfetto (reference:
    ray.timeline, _private/state.py:828 chrome_tracing_dump)."""
    events = list_tasks()
    trace = []
    for e in events:
        trace.append({
            "name": e["name"], "cat": "task", "ph": "X",
            "ts": e["start"] * 1e6, "dur": (e["end"] - e["start"]) * 1e6,
            "pid": e["pid"], "tid": e["worker_id"],
            "args": {"task_id": e["task_id"], "actor_id": e["actor_id"]},
        })
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


class profile_device:
    """Capture an XLA/TPU device trace alongside the task timeline
    (reference gap noted in SURVEY §5.1: the reference merges Ray task
    events only; JAX's profiler captures the device side).

    Usage:
        with ray_tpu.util.state.profile_device("/tmp/trace"):
            train_step(...)
        ray_tpu.timeline("tasks.json")   # task-level chrome trace

    The device trace lands in TensorBoard/XProf format under `logdir`
    ("tensorboard --logdir" or xprof to view); the task timeline stays
    chrome-trace.  The two share wall-clock timestamps, so aligning a
    slow task with its device activity is a same-axis comparison.
    Degrades to a no-op (with a warning) where the backend has no
    profiler support (e.g. some tunneled TPU plugins).
    """

    def __init__(self, logdir: str):
        self.logdir = logdir
        self._active = False

    def __enter__(self):
        try:
            import jax

            jax.profiler.start_trace(self.logdir)
            self._active = True
        except Exception as e:  # noqa: BLE001 - no profiler support
            import logging

            logging.getLogger(__name__).warning(
                "device profiler unavailable (%s); task timeline still "
                "records", e)
        return self

    def __exit__(self, *exc):
        if self._active:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
        return False
