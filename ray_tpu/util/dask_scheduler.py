"""Dask-on-ray_tpu scheduler: execute dask task graphs as remote tasks.

Role-equivalent of the reference's Dask integration (reference
``python/ray/util/dask/scheduler.py`` — a ``get`` implementation
submitting one remote task per graph node, dependencies flowing as
object refs).  The graph-protocol helpers (a task is
``(callable, *args)``; args may be keys or nested lists/tasks) are
implemented locally, so this module works as
``dask.compute(..., scheduler=ray_tpu_dask_get)`` when dask is
installed and is unit-testable on plain dict graphs without it.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

_TASK_MARK = "__raytpu_dask_task__"


def _ishashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _istask(x) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _toposort(dsk: Dict) -> List[Hashable]:
    seen: set = set()
    out: List[Hashable] = []

    def deps_of(spec, acc):
        if _istask(spec):
            for a in spec[1:]:
                deps_of(a, acc)
        elif isinstance(spec, list):
            for a in spec:
                deps_of(a, acc)
        elif _ishashable(spec) and spec in dsk:
            acc.append(spec)

    def visit(key, stack):
        if key in seen:
            return
        if key in stack:
            raise ValueError(f"cycle in dask graph at {key!r}")
        stack.add(key)
        acc: List = []
        deps_of(dsk[key], acc)
        for d in acc:
            visit(d, stack)
        stack.discard(key)
        seen.add(key)
        out.append(key)

    for key in dsk:
        visit(key, set())
    return out


def _eval_spec(spec):
    """Worker-side evaluation of a substituted task spec: ObjectRefs are
    fetched, nested task nodes applied, containers recursed."""
    if isinstance(spec, ray_tpu.ObjectRef):
        return ray_tpu.get(spec)
    if isinstance(spec, tuple) and len(spec) == 3 and spec[0] == _TASK_MARK:
        _, fn, args = spec
        return fn(*[_eval_spec(a) for a in args])
    if isinstance(spec, list):
        return [_eval_spec(a) for a in spec]
    return spec


def _exec_dask_node(spec):
    return _eval_spec(spec)


def ray_tpu_dask_get(dsk: Dict, keys, **_kwargs):
    """The dask ``get``: pass as ``scheduler=`` to ``dask.compute``
    (reference: ray_dask_get, util/dask/scheduler.py)."""
    ray_tpu._auto_init()
    exec_node = ray_tpu.remote(num_cpus=1)(_exec_dask_node)
    refs: Dict[Hashable, Any] = {}

    def substitute(spec):
        if _istask(spec):
            return (_TASK_MARK, spec[0],
                    [substitute(a) for a in spec[1:]])
        if isinstance(spec, list):
            return [substitute(a) for a in spec]
        if _ishashable(spec) and spec in refs:
            return refs[spec]
        return spec

    for key in _toposort(dsk):
        spec = dsk[key]
        if _istask(spec):
            refs[key] = exec_node.remote(substitute(spec))
        elif _ishashable(spec) and spec in refs:
            refs[key] = refs[spec]  # alias key
        else:
            refs[key] = ray_tpu.put(substitute(spec))

    def fetch(k):
        if isinstance(k, list):
            return [fetch(x) for x in k]
        return ray_tpu.get(refs[k], timeout=600)

    return fetch(keys)
