"""multiprocessing.Pool drop-in backed by tasks (reference analog:
python/ray/util/multiprocessing/pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List):
        self._refs = refs

    def get(self, timeout: Optional[float] = None):
        return ray_tpu.get(self._refs, timeout=timeout or 300)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)


class Pool:
    """Parallelism comes from the cluster, not local forks; `processes`
    caps in-flight tasks."""

    def __init__(self, processes: Optional[int] = None):
        self._limit = processes or int(
            ray_tpu.cluster_resources().get("CPU", 1))

    def _task(self, func):
        return ray_tpu.remote(func)

    def map(self, func: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(func, iterable).get()

    def map_async(self, func: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        remote_fn = self._task(func)
        refs = []
        items = list(iterable)
        for i in range(0, len(items), max(1, self._limit)):
            window = items[i:i + max(1, self._limit)]
            refs.extend(remote_fn.remote(x) for x in window)
        return AsyncResult(refs)

    def starmap(self, func: Callable, iterable: Iterable) -> List[Any]:
        remote_fn = self._task(func)
        refs = [remote_fn.remote(*args) for args in iterable]
        return ray_tpu.get(refs, timeout=300)

    def apply(self, func: Callable, args: tuple = (),
              kwds: Optional[dict] = None):
        return ray_tpu.get(
            self._task(func).remote(*args, **(kwds or {})), timeout=300)

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        return AsyncResult([self._task(func).remote(*args,
                                                    **(kwds or {}))])

    def imap(self, func: Callable, iterable: Iterable):
        remote_fn = self._task(func)
        refs = [remote_fn.remote(x) for x in iterable]
        for ref in refs:
            yield ray_tpu.get(ref, timeout=300)

    def close(self) -> None:
        pass

    def terminate(self) -> None:
        pass

    def join(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
