"""Scheduling strategy objects accepted by @remote(scheduling_strategy=...)
(reference analog: python/ray/util/scheduling_strategies.py)."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    node_id: bytes
    soft: bool = False
