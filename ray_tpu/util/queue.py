"""Distributed FIFO queue backed by an actor (reference analog:
python/ray/util/queue.py)."""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        import collections

        self.maxsize = maxsize
        self.items = collections.deque()

    def qsize(self) -> int:
        return len(self.items)

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self):
        if not self.items:
            return False, None
        return True, self.items.popleft()

    def put_batch(self, items) -> int:
        n = 0
        for item in items:
            if self.maxsize > 0 and len(self.items) >= self.maxsize:
                break
            self.items.append(item)
            n += 1
        return n


class Queue:
    def __init__(self, maxsize: int = 0, *,
                 actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        self._actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item), timeout=30):
                return
            if not block or (deadline and time.monotonic() > deadline):
                raise Full("queue full")
            time.sleep(0.05)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get.remote(), timeout=30)
            if ok:
                return item
            if not block or (deadline and time.monotonic() > deadline):
                raise Empty("queue empty")
            time.sleep(0.05)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except Exception:  # noqa: BLE001
            pass

    def __reduce__(self):
        return (_rebuild_queue, (self._actor,))


def _rebuild_queue(actor):
    q = object.__new__(Queue)
    q._actor = actor
    return q
