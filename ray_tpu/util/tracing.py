"""Spans for task/actor calls, with cross-process context propagation.

Role-equivalent of the reference's tracing helper (reference
``python/ray/util/tracing/tracing_helper.py:33 _OpenTelemetryProxy``,
``:160 _DictPropagator`` — spans wrap task submission/execution and the
trace context rides the task metadata).  Opt-in per process via
``enable_tracing()``.

Backends, best available first:
* opentelemetry-sdk installed → real OTel spans through any SpanExporter
  (default: in-memory, readable via recorded_spans());
* only opentelemetry-api (or nothing) → a minimal built-in recorder with
  the same surface: spans still link across processes through the
  ``trace_ctx`` carrier on the task spec.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_enabled = False
_mode = ""  # "otel" | "fallback"
_memory_spans: Optional[Any] = None


@dataclasses.dataclass
class SpanRecord:
    """Fallback span (surface-compatible with the bits tests read).

    ``start`` is on the process monotonic clock (``time.perf_counter``)
    — the same domain as telemetry, flightrec, and the device
    observatory — so fallback spans can render into a shared timeline.
    ``duration`` is 0.0 for point spans recorded without an end."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float = 0.0
    duration: float = 0.0


_fallback_spans: List[SpanRecord] = []
_fallback_lock = threading.Lock()


def _try_otel_sdk():
    try:
        from opentelemetry import propagate, trace
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import SimpleSpanProcessor

        return trace, propagate, TracerProvider, SimpleSpanProcessor
    except ImportError:
        return None, None, None, None


def enable_tracing(exporter: Optional[Any] = None) -> bool:
    """Turn on span recording in this process."""
    global _enabled, _mode, _memory_spans
    if _enabled:
        return True
    trace, _prop, TracerProvider, SimpleSpanProcessor = _try_otel_sdk()
    if trace is not None:
        provider = trace.get_tracer_provider()
        if not isinstance(provider, TracerProvider):
            provider = TracerProvider()
            trace.set_tracer_provider(provider)
        if exporter is None:
            from opentelemetry.sdk.trace.export.in_memory_span_exporter \
                import InMemorySpanExporter

            _memory_spans = InMemorySpanExporter()
            exporter = _memory_spans
        provider.add_span_processor(SimpleSpanProcessor(exporter))
        _mode = "otel"
    else:
        _mode = "fallback"
    _enabled = True
    return True


def is_enabled() -> bool:
    return _enabled


def reset_tracing() -> None:
    """Clear all tracing state in this process: the fallback span list,
    the in-memory OTel exporter, and the enabled flag/mode — so tests
    sharing one process don't leak spans or the enabled bit into each
    other (test fixtures call this after every test).

    OTel caveat: the global TracerProvider can't drop an added
    SpanProcessor, so after a reset a re-enable under the otel backend
    attaches a fresh in-memory exporter and the stale processor keeps
    exporting into the cleared (now unreferenced) one — harmless."""
    global _enabled, _mode, _memory_spans
    with _fallback_lock:
        _fallback_spans.clear()
    if _memory_spans is not None:
        try:
            _memory_spans.clear()
        except Exception:  # noqa: BLE001 - exporter already shut down
            pass
    _memory_spans = None
    _enabled = False
    _mode = ""


def record_span(name: str, trace_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                start: Optional[float] = None,
                duration: float = 0.0):
    """Record one standalone span event and return its identity as a
    ``(trace_id, span_id)`` pair (None when tracing is off).  The serve
    engine telemetry uses this to link a request's root span to the
    engine-side work span: pass the returned pair back as
    ``trace_id``/``parent_id`` to record a child.  ``start``/``duration``
    (monotonic seconds) stamp the fallback record so it can render into
    a timeline; under the OTel backend the span carries its own clock
    and the hints are ignored."""
    if not _enabled:
        return None
    if _mode == "otel":
        from opentelemetry import trace

        tracer = trace.get_tracer("ray_tpu")
        with tracer.start_as_current_span(name) as span:
            ctx = span.get_span_context()
        return (format(ctx.trace_id, "032x"),
                format(ctx.span_id, "016x"))
    tid = trace_id or uuid.uuid4().hex
    return (tid, _record(name, tid, parent_id,
                         start=start, duration=duration))


def recorded_spans() -> List[Any]:
    if _mode == "otel" and _memory_spans is not None:
        return list(_memory_spans.get_finished_spans())
    with _fallback_lock:
        return list(_fallback_spans)


def _record(name: str, trace_id: str, parent_id: Optional[str],
            start: Optional[float] = None,
            duration: float = 0.0) -> str:
    span_id = uuid.uuid4().hex[:16]
    if start is None:
        start = time.perf_counter()
    with _fallback_lock:
        _fallback_spans.append(
            SpanRecord(name, trace_id, span_id, parent_id,
                       start, duration))
        if len(_fallback_spans) > 10_000:
            del _fallback_spans[:5_000]
    return span_id


def maybe_inject(kind: str, name: str) -> Optional[Dict[str, str]]:
    """Submitter side: open a submission span and return the carrier to
    ride the task spec (None when tracing is off)."""
    if not _enabled:
        return None
    label = f"{kind} {name}.remote()"
    if _mode == "otel":
        from opentelemetry import propagate, trace

        tracer = trace.get_tracer("ray_tpu")
        with tracer.start_as_current_span(label):
            carrier: Dict[str, str] = {}
            propagate.inject(carrier)
        return carrier or None
    trace_id = uuid.uuid4().hex
    span_id = _record(label, trace_id, None)
    return {"raytpu-trace": f"{trace_id}:{span_id}"}


@contextlib.contextmanager
def task_span(name: str, carrier: Optional[Dict[str, str]]):
    """Executor side: child span around user code, parented by the
    submitter's context from the spec.  Workers lazily enable tracing on
    the first traced task they see."""
    if not carrier:
        yield
        return
    if not _enabled:
        enable_tracing()
    label = f"execute {name}"
    if _mode == "otel" and "raytpu-trace" not in carrier:
        from opentelemetry import propagate, trace

        ctx = propagate.extract(carrier)
        tracer = trace.get_tracer("ray_tpu")
        with tracer.start_as_current_span(label, context=ctx):
            yield
        return
    ref = carrier.get("raytpu-trace", ":")
    trace_id, parent = (ref.split(":") + [""])[:2]
    _record(label, trace_id or uuid.uuid4().hex, parent or None)
    yield
