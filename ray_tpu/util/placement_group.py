"""Placement groups: gang resource reservation.

Reference analog: python/ray/util/placement_group.py (:128
placement_group(), :33 class PlacementGroup); the GCS side implements the
2PC prepare/commit bundle reservation (reference
gcs_placement_group_scheduler.h:103-105) in ray_tpu/_private/gcs.py.

TPU-first role: a STRICT_PACK group over {"TPU": n} bundles is how a
trainer reserves one ICI domain (a whole slice) so its collectives never
cross DCN.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private import worker_context
from ray_tpu._private.ids import PlacementGroupID

_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]]):
        self._id = pg_id
        self.bundles = bundles

    @property
    def id(self) -> PlacementGroupID:
        return PlacementGroupID(self._id)

    def ready(self, timeout: float = 60.0) -> "PlacementGroup":
        """Block until all bundles are reserved (2PC committed)."""
        cw = worker_context.core_worker()
        info = cw.io.run(cw.gcs.call("pg_wait_ready", {"pg_id": self._id},
                                     timeout=timeout))
        if info["state"] != "CREATED":
            raise RuntimeError(
                f"placement group not ready: state={info['state']}")
        return self

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def __reduce__(self):
        return (PlacementGroup, (self._id, self.bundles))

    def __repr__(self):
        return f"PlacementGroup({PlacementGroupID(self._id).hex()[:16]})"


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    """Reserve a gang of resource bundles across the cluster."""
    if strategy not in _STRATEGIES:
        raise ValueError(f"strategy must be one of {_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    import ray_tpu

    ray_tpu._auto_init()
    cw = worker_context.core_worker()
    pg_id = PlacementGroupID.from_random().binary()
    cw.io.run(cw.gcs.call("pg_create", {
        "pg_id": pg_id, "name": name,
        "bundles": [{k: float(v) for k, v in b.items()} for b in bundles],
        "strategy": strategy}))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup, timeout: float = 30.0):
    cw = worker_context.core_worker()
    cw.io.run(cw.gcs.call("pg_remove", {"pg_id": pg.id.binary()},
                          timeout=timeout))


def get_placement_group(name: str) -> Optional[PlacementGroup]:
    cw = worker_context.core_worker()
    pgs = cw.io.run(cw.gcs.call("pg_list", {}))
    for info in pgs:
        if info["name"] == name and info["state"] != "REMOVED":
            return PlacementGroup(info["pg_id"], info["bundles"])
    return None
