"""joblib backend: scikit-learn style Parallel() on ray_tpu actors/tasks.

Role-equivalent of the reference's joblib integration (reference
``python/ray/util/joblib/ray_backend.py`` — a ParallelBackendBase whose
apply_async submits remote tasks).  Usage:

    from ray_tpu.util.joblib_backend import register_ray_tpu
    import joblib

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        joblib.Parallel()(joblib.delayed(f)(x) for x in data)
"""

from __future__ import annotations

from typing import Any, Callable, Optional


def register_ray_tpu() -> None:
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)


class _ResultHandle:
    """joblib future surface over an ObjectRef."""

    def __init__(self, ref, callback: Optional[Callable]):
        self._ref = ref
        self._callback = callback
        self._done = False
        self._value: Any = None

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        if not self._done:
            self._value = ray_tpu.get(self._ref, timeout=timeout)
            self._done = True
        return self._value


def _make_backend_cls():
    from joblib.parallel import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        uses_threads = False
        supports_sharedmem = False

        def configure(self, n_jobs=1, parallel=None, **_kw):
            import ray_tpu

            ray_tpu._auto_init()
            self.parallel = parallel
            self._n_jobs = self.effective_n_jobs(n_jobs)
            return self._n_jobs

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs is None or n_jobs < 0:
                return max(1, cpus)
            return n_jobs

        def apply_async(self, func, callback=None):
            import ray_tpu

            @ray_tpu.remote(num_cpus=1)
            def _run_joblib_batch(f):
                return f()

            ref = _run_joblib_batch.remote(func)
            handle = _ResultHandle(ref, callback)
            if callback is not None:
                # joblib drives completion through callbacks; resolve on
                # a helper thread so Parallel() keeps dispatching.
                import threading

                def waiter():
                    try:
                        handle.get()
                    except Exception:  # noqa: BLE001 - surfaced by get
                        pass
                    callback(handle)

                threading.Thread(target=waiter, daemon=True).start()
            return handle

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self._n_jobs,
                               parallel=self.parallel)

    return RayTpuBackend


try:
    RayTpuBackend = _make_backend_cls()
except ImportError:  # joblib not installed: register_ray_tpu will raise
    RayTpuBackend = None  # type: ignore[assignment]
