"""Serializability inspection (reference analog:
python/ray/util/check_serialize.py inspect_serializability) — walk an
object and report WHICH nested component fails to pickle, instead of
the bare TypeError cloudpickle raises from the middle of a task
submission."""

from __future__ import annotations

import inspect
from typing import Any, List, Set, Tuple

try:
    import cloudpickle
except ImportError:                              # pragma: no cover
    import pickle as cloudpickle


def _try(obj: Any) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:  # noqa: BLE001 - any failure means unserializable
        return False


def _describe(obj: Any) -> str:
    name = getattr(obj, "__qualname__", None) or \
        getattr(obj, "__name__", None) or repr(obj)[:80]
    return f"{type(obj).__name__} {name}"


def inspect_serializability(obj: Any, name: str = "<root>",
                            _depth: int = 0, _seen: Set[int] = None,
                            _failures: List[str] = None
                            ) -> Tuple[bool, List[str]]:
    """Returns (serializable, failure descriptions).  On failure,
    recurses into closures, attributes, and containers to pinpoint the
    leaf objects that cannot pickle (locks, sockets, loggers with
    handlers, live clients...)."""
    _seen = _seen if _seen is not None else set()
    _failures = _failures if _failures is not None else []
    if id(obj) in _seen or _depth > 4:
        return not _failures, _failures
    _seen.add(id(obj))
    if _try(obj):
        return not _failures, _failures

    children = []
    closure = getattr(obj, "__closure__", None)
    if closure:
        names = obj.__code__.co_freevars
        children += [(f"{name}.<closure>.{n}", c.cell_contents)
                     for n, c in zip(names, closure)
                     if c.cell_contents is not obj]
    d = getattr(obj, "__dict__", None)
    if isinstance(d, dict):
        children += [(f"{name}.{k}", v) for k, v in d.items()]
    if isinstance(obj, dict):
        children += [(f"{name}[{k!r}]", v) for k, v in obj.items()]
    elif isinstance(obj, (list, tuple, set)):
        children += [(f"{name}[{i}]", v)
                     for i, v in enumerate(obj)]

    found_child = False
    for child_name, child in children:
        if not _try(child):
            found_child = True
            inspect_serializability(child, child_name, _depth + 1,
                                    _seen, _failures)
    if not found_child:
        # this object itself is the unserializable leaf
        _failures.append(f"{name}: {_describe(obj)}")
    return False, _failures
