"""ParallelIterator: actor-sharded lazy iterators.

Role-equivalent of the reference's ``python/ray/util/iter.py:132
ParallelIterator`` (``:1136 ParallelIteratorWorker``): a list of item
shards hosted by actors, transformed lazily (for_each/filter/batch),
consumed synchronously or asynchronously on the driver.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ParallelIteratorWorker:
    """Actor hosting one shard's (possibly infinite) item stream
    (reference: util/iter.py:1136)."""

    def __init__(self, items, repeat: bool = False):
        self._base = items
        self._repeat = repeat
        self._transforms: List = []
        self._it: Optional[Iterator] = None

    def add_transform(self, fn_ser: bytes) -> bool:
        import cloudpickle

        self._transforms.append(cloudpickle.loads(fn_ser))
        self._it = None  # restart with the new pipeline
        return True

    def _build(self) -> Iterator:
        base = self._base() if callable(self._base) else self._base

        def gen():
            while True:
                for x in (base() if callable(base) else list(base)):
                    yield x
                if not self._repeat:
                    return

        it: Iterator = gen()
        for t in self._transforms:
            it = t(it)
        return it

    def next_batch(self, n: int = 1):
        """Up to n items; [] = exhausted (StopIteration sentinel)."""
        if self._it is None:
            self._it = self._build()
        return list(itertools.islice(self._it, n))


class LocalIterator:
    """Driver-side view over gathered results (reference: the
    gather_sync return type)."""

    def __init__(self, gen_factory: Callable[[], Iterator]):
        self._factory = gen_factory

    def __iter__(self):
        return self._factory()

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(iter(self), n))


class ParallelIterator:
    def __init__(self, actors: List, batch_fetch: int = 16):
        self.actors = actors
        self._batch_fetch = batch_fetch

    # -- constructors ------------------------------------------------------

    @staticmethod
    def from_items(items: List[Any], num_shards: int = 2,
                   repeat: bool = False) -> "ParallelIterator":
        shards = [items[i::num_shards] for i in range(num_shards)]
        return ParallelIterator.from_iterators(shards, repeat=repeat)

    @staticmethod
    def from_range(n: int, num_shards: int = 2,
                   repeat: bool = False) -> "ParallelIterator":
        return ParallelIterator.from_items(list(range(n)), num_shards,
                                           repeat)

    @staticmethod
    def from_iterators(generators: List[Iterable],
                       repeat: bool = False) -> "ParallelIterator":
        cls = ray_tpu.remote(num_cpus=0.1)(ParallelIteratorWorker)
        actors = [cls.remote(g, repeat) for g in generators]
        return ParallelIterator(actors)

    # -- lazy transforms ---------------------------------------------------

    def _with_transform(self, make_t) -> "ParallelIterator":
        import cloudpickle

        ser = cloudpickle.dumps(make_t)
        ray_tpu.get([a.add_transform.remote(ser) for a in self.actors],
                    timeout=60)
        return self

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._with_transform(lambda it: map(fn, it))

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._with_transform(lambda it: (x for x in it if fn(x)))

    def batch(self, n: int) -> "ParallelIterator":
        def t(it):
            while True:
                b = list(itertools.islice(it, n))
                if not b:
                    return
                yield b

        return self._with_transform(t)

    def flatten(self) -> "ParallelIterator":
        return self._with_transform(
            lambda it: (y for x in it for y in x))

    # -- consumption -------------------------------------------------------

    def num_shards(self) -> int:
        return len(self.actors)

    def gather_sync(self) -> LocalIterator:
        """Round-robin over shards, in order (reference:
        iter.py gather_sync)."""
        fetch = self._batch_fetch

        def gen():
            live = list(self.actors)
            buffers = {a: [] for a in live}
            while live:
                for a in list(live):
                    if not buffers[a]:
                        buffers[a] = ray_tpu.get(
                            a.next_batch.remote(fetch), timeout=300)
                        if not buffers[a]:
                            live.remove(a)
                            continue
                    yield buffers[a].pop(0)

        return LocalIterator(gen)

    def gather_async(self) -> LocalIterator:
        """Items in completion order across shards (reference:
        iter.py gather_async)."""
        fetch = self._batch_fetch

        def gen():
            inflight = {a.next_batch.remote(fetch): a
                        for a in self.actors}
            while inflight:
                ready, _ = ray_tpu.wait(list(inflight), num_returns=1,
                                        timeout=300)
                for ref in ready:
                    actor = inflight.pop(ref)
                    batch = ray_tpu.get(ref, timeout=60)
                    if batch:
                        inflight[actor.next_batch.remote(fetch)] = actor
                        yield from batch

        return LocalIterator(gen)

    def take(self, n: int) -> List[Any]:
        return self.gather_sync().take(n)

    def stop(self) -> None:
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
