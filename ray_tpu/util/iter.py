"""ParallelIterator: actor-sharded lazy iterators.

Role-equivalent of the reference's ``python/ray/util/iter.py:132
ParallelIterator`` (``:1136 ParallelIteratorWorker``): a list of item
shards hosted by actors, transformed lazily (for_each/filter/batch),
consumed synchronously or asynchronously on the driver.

Transforms are value-like: each ``for_each``/``filter``/... returns a
NEW ParallelIterator carrying its own transform chain; the chain is
shipped to the shard actors only at consumption time, so branching one
iterator into several pipelines never contaminates siblings.  (The
shard ACTORS are shared between branches — consume branches
sequentially, and note that generator-backed shards are single-shot.)
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ParallelIteratorWorker:
    """Actor hosting one shard's (possibly repeating) item stream
    (reference: util/iter.py:1136)."""

    def __init__(self, items, repeat: bool = False):
        self._base = items
        self._repeat = repeat
        self._transforms: List = []
        self._it: Optional[Iterator] = None

    def set_transforms(self, fns_ser: bytes) -> bool:
        import cloudpickle

        self._transforms = cloudpickle.loads(fns_ser)
        self._it = None  # restart with the new pipeline
        return True

    def _build(self) -> Iterator:
        base = self._base

        def gen():
            while True:
                produced = False
                for x in (base() if callable(base) else list(base)):
                    produced = True
                    yield x
                # an exhausted/empty source must END even under repeat —
                # otherwise this loop would spin forever yielding nothing
                if not self._repeat or not produced:
                    return

        it: Iterator = gen()
        for t in self._transforms:
            it = t(it)
        return it

    def next_batch(self, n: int = 1):
        """Up to n items; [] = exhausted (StopIteration sentinel)."""
        if self._it is None:
            self._it = self._build()
        return list(itertools.islice(self._it, n))


class LocalIterator:
    """Driver-side view over gathered results (reference: the
    gather_sync return type)."""

    def __init__(self, gen_factory: Callable[[], Iterator]):
        self._factory = gen_factory

    def __iter__(self):
        return self._factory()

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(iter(self), n))


class ParallelIterator:
    def __init__(self, actors: List, transforms: Optional[List] = None,
                 batch_fetch: int = 16):
        self.actors = actors
        self._transforms: List[Callable] = list(transforms or [])
        self._batch_fetch = batch_fetch

    # -- constructors ------------------------------------------------------

    @staticmethod
    def from_items(items: List[Any], num_shards: int = 2,
                   repeat: bool = False) -> "ParallelIterator":
        shards = [items[i::num_shards] for i in range(num_shards)]
        return ParallelIterator.from_iterators(shards, repeat=repeat)

    @staticmethod
    def from_range(n: int, num_shards: int = 2,
                   repeat: bool = False) -> "ParallelIterator":
        return ParallelIterator.from_items(list(range(n)), num_shards,
                                           repeat)

    @staticmethod
    def from_iterators(generators: List[Iterable],
                       repeat: bool = False) -> "ParallelIterator":
        cls = ray_tpu.remote(num_cpus=0.1)(ParallelIteratorWorker)
        actors = [cls.remote(g, repeat) for g in generators]
        return ParallelIterator(actors)

    # -- lazy transforms (value-like: new iterator per call) ---------------

    def _with_transform(self, t: Callable) -> "ParallelIterator":
        return ParallelIterator(self.actors, self._transforms + [t],
                                self._batch_fetch)

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._with_transform(
            lambda it, _fn=fn: map(_fn, it))

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._with_transform(
            lambda it, _fn=fn: (x for x in it if _fn(x)))

    def batch(self, n: int) -> "ParallelIterator":
        def t(it, _n=n):
            while True:
                b = list(itertools.islice(it, _n))
                if not b:
                    return
                yield b

        return self._with_transform(t)

    def flatten(self) -> "ParallelIterator":
        return self._with_transform(
            lambda it: (y for x in it for y in x))

    # -- consumption -------------------------------------------------------

    def num_shards(self) -> int:
        return len(self.actors)

    def _install(self) -> None:
        import cloudpickle

        ser = cloudpickle.dumps(self._transforms)
        ray_tpu.get([a.set_transforms.remote(ser) for a in self.actors],
                    timeout=60)

    def gather_sync(self) -> LocalIterator:
        """Round-robin over shards, in order (reference:
        iter.py gather_sync)."""
        fetch = self._batch_fetch

        def gen():
            self._install()
            live = list(self.actors)
            buffers = {a: [] for a in live}
            while live:
                for a in list(live):
                    if not buffers[a]:
                        buffers[a] = ray_tpu.get(
                            a.next_batch.remote(fetch), timeout=300)
                        if not buffers[a]:
                            live.remove(a)
                            continue
                    yield buffers[a].pop(0)

        return LocalIterator(gen)

    def gather_async(self) -> LocalIterator:
        """Items in completion order across shards (reference:
        iter.py gather_async)."""
        fetch = self._batch_fetch

        def gen():
            self._install()
            inflight = {a.next_batch.remote(fetch): a
                        for a in self.actors}
            while inflight:
                ready, _ = ray_tpu.wait(list(inflight), num_returns=1,
                                        timeout=300)
                for ref in ready:
                    actor = inflight.pop(ref)
                    batch = ray_tpu.get(ref, timeout=60)
                    if batch:
                        inflight[actor.next_batch.remote(fetch)] = actor
                        yield from batch

        return LocalIterator(gen)

    def take(self, n: int) -> List[Any]:
        return self.gather_sync().take(n)

    def stop(self) -> None:
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
