"""Distributed two-phase shuffle over object-store blocks.

Role-equivalent of the reference's push-based shuffle
(``python/ray/data/_internal/push_based_shuffle.py``): a map phase
partitions every block into N sub-blocks (one multi-return remote task
per block — sub-blocks flow through the object store, never the
driver), and a reduce phase builds each output block from its N_map
parts.  The driver only routes ObjectRefs.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_util


def _partition_random(table, n_out: int, seed: int):
    rng = np.random.RandomState(seed)
    assign = rng.randint(0, n_out, size=table.num_rows)
    return [table.take(np.nonzero(assign == p)[0]) for p in range(n_out)]


def _partition_range(table, key: str, cuts, descending: bool):
    col = table.column(key).to_numpy(zero_copy_only=False)
    idx = np.searchsorted(cuts, col, side="right")
    if descending:
        idx = len(cuts) - idx
        idx = np.clip(idx, 0, len(cuts))
    return [table.take(np.nonzero(idx == p)[0])
            for p in range(len(cuts) + 1)]


def _stable_hash(x) -> int:
    """Deterministic across processes — Python's hash() is salted per
    process (PYTHONHASHSEED), which would scatter equal string keys into
    different partitions on different workers."""
    import zlib

    return zlib.crc32(repr(x).encode())


def _partition_hash(table, key: str, n_out: int):
    col = table.column(key).to_numpy(zero_copy_only=False)
    hashes = np.array([_stable_hash(x) % n_out for x in col.tolist()])
    return [table.take(np.nonzero(hashes == p)[0]) for p in range(n_out)]


@ray_tpu.remote
def _reduce_concat(*parts):
    live = [p for p in parts if p.num_rows]
    if not live:
        return parts[0]
    return block_util.concat_tables(live)


@ray_tpu.remote
def _reduce_sorted(key, descending, *parts):
    live = [p for p in parts if p.num_rows] or [parts[0]]
    big = block_util.concat_tables(live)
    order = "descending" if descending else "ascending"
    return big.sort_by([(key, order)])


def _two_phase(block_refs: List, n_out: int, submit_map,
               reduce_remote, reduce_args=()) -> List:
    """map: block -> n_out parts (multi-return, submitted via the
    ``submit_map(block)`` callable); reduce: column of parts -> one
    output block."""
    maps = [submit_map(b) for b in block_refs]
    if n_out == 1:
        maps = [[m] for m in maps]
    return [reduce_remote.remote(*reduce_args,
                                 *[maps[m][p] for m in range(len(maps))])
            for p in range(n_out)]


@ray_tpu.remote
def _shuffle_map(table, seed: int, n_out: int):
    return tuple(_partition_random(table, n_out, seed)) \
        if n_out > 1 else table


#: at/above this many input blocks the exchanges switch to the
#: push-based plan (bounded fan-in, pipelined merges); below it the
#: simple two-phase exchange has less task overhead
PUSH_BASED_THRESHOLD = 16


def shuffle_blocks(block_refs: List, n_out: int,
                   seed: Optional[int] = None) -> List:
    """Random shuffle: every output block gets rows from every input."""
    base = np.random.RandomState(seed).randint(0, 2**31) \
        if seed is not None else np.random.randint(0, 2**31)

    counter = iter(range(len(block_refs)))

    def submit_map(b):
        return _shuffle_map.options(num_returns=n_out).remote(
            b, base + next(counter), n_out)

    if len(block_refs) >= PUSH_BASED_THRESHOLD:
        return push_based_shuffle(block_refs, n_out, submit_map,
                                  _reduce_concat)
    return _two_phase(block_refs, n_out, submit_map, _reduce_concat)


def sort_blocks(block_refs: List, key: str, descending: bool,
                n_out: int) -> List:
    """Sample-based range-partitioned distributed sort (reference:
    sort_impl's boundary sampling)."""
    @ray_tpu.remote
    def _sample(table):
        col = table.column(key).to_numpy(zero_copy_only=False)
        if len(col) == 0:
            return col
        k = min(64, len(col))
        idx = np.random.RandomState(0).choice(len(col), size=k,
                                              replace=False)
        return col[idx]

    samples = np.concatenate(
        [s for s in ray_tpu.get([_sample.remote(b) for b in block_refs],
                                timeout=300) if len(s)] or
        [np.array([0.0])])
    samples = np.sort(samples)
    cuts = [samples[int(len(samples) * (i + 1) / n_out)]
            for i in range(n_out - 1)] if n_out > 1 else []
    cuts_arr = np.asarray(sorted(set(cuts))) if cuts else np.asarray([])
    n_parts = len(cuts_arr) + 1

    @ray_tpu.remote
    def _map(table):
        parts = _partition_range(table, key, cuts_arr, descending)
        return tuple(parts) if n_parts > 1 else parts[0]

    def submit_map(b):
        return _map.options(num_returns=n_parts).remote(b)

    # descending partitions are already emitted highest-first by
    # _partition_range's index flip
    if len(block_refs) >= PUSH_BASED_THRESHOLD:
        return push_based_shuffle(block_refs, n_parts, submit_map,
                                  _reduce_sorted,
                                  reduce_args=(key, descending))
    return _two_phase(block_refs, n_parts, submit_map, _reduce_sorted,
                      reduce_args=(key, descending))


def hash_partition_blocks(block_refs: List, key: str, n_out: int) -> List:
    """Co-locate equal keys in the same output block (groupby basis)."""
    @ray_tpu.remote
    def _map(table):
        parts = _partition_hash(table, key, n_out)
        return tuple(parts) if n_out > 1 else parts[0]

    submit_map = lambda b: _map.options(num_returns=n_out).remote(b)
    if len(block_refs) >= PUSH_BASED_THRESHOLD:
        return push_based_shuffle(block_refs, n_out, submit_map,
                                  _reduce_concat)
    return _two_phase(block_refs, n_out, submit_map, _reduce_concat)


# ---------------------------------------------------------------------------
# Push-based shuffle
# ---------------------------------------------------------------------------

@ray_tpu.remote
def _merge_parts(k: int, n_maps: int, *parts):
    """Merge one round's sub-blocks for k partitions.  ``parts`` is laid
    out map-major: parts[m*k + i] is map m's piece of partition i."""
    out = []
    for i in range(k):
        live = [parts[m * k + i] for m in range(n_maps)
                if parts[m * k + i].num_rows]
        out.append(block_util.concat_tables(live) if live
                   else parts[i])
    return tuple(out) if k > 1 else out[0]


def push_based_shuffle(block_refs: List, n_out: int, submit_map,
                       reduce_remote, reduce_args=(), *,
                       round_size: int = 0,
                       merge_factor: int = 2) -> List:
    """Two-level pipelined exchange (reference:
    data/_internal/push_based_shuffle.py:1 — redesigned around this
    runtime's multi-return tasks instead of actor-pinned merge stages).

    The naive two-phase exchange gives every reduce task ``n_maps``
    arguments — at 1000 input blocks each reduce pulls 1000 tiny
    objects, and the driver materializes an n_maps×n_out ref matrix.
    Here maps run in ROUNDS of ``round_size``; each round's outputs are
    immediately combined by merge tasks (each owning a contiguous range
    of ~``merge_factor`` partitions) while the NEXT round's maps
    already execute — map compute and merge I/O pipeline.  Fan-in is
    bounded: merge tasks take round_size×k args, reduce tasks take one
    merged piece per round."""
    n_maps = len(block_refs)
    if not n_maps:
        return []
    if round_size <= 0:
        cpus = ray_tpu.cluster_resources().get("CPU", 2)
        round_size = max(2, int(cpus))
    n_rounds = -(-n_maps // round_size)
    n_merge = max(1, n_out // max(1, merge_factor))
    # contiguous partition ranges per merge task
    bounds = [round(j * n_out / n_merge) for j in range(n_merge + 1)]
    pieces: List[List] = [[] for _ in range(n_out)]  # per part, per round
    prev_merges: List = []
    for r in range(n_rounds):
        blocks = block_refs[r * round_size:(r + 1) * round_size]
        maps = [submit_map(b) for b in blocks]
        if n_out == 1:
            maps = [[m] for m in maps]
        # backpressure: at most two rounds in flight — wait for the
        # round-before-last's merges before growing the frontier
        if prev_merges:
            remaining = prev_merges
            while remaining:
                _, remaining = ray_tpu.wait(
                    remaining, num_returns=len(remaining), timeout=60.0)
        prev_merges = []
        for j in range(n_merge):
            lo, hi = bounds[j], bounds[j + 1]
            k = hi - lo
            if k <= 0:
                continue
            args = [maps[m][p] for m in range(len(maps))
                    for p in range(lo, hi)]
            merged = _merge_parts.options(num_returns=k).remote(
                k, len(maps), *args)
            if k == 1:
                merged = [merged]
            for i, p in enumerate(range(lo, hi)):
                pieces[p].append(merged[i])
            prev_merges.extend(merged)
    return [reduce_remote.remote(*reduce_args, *pieces[p])
            for p in range(n_out)]
