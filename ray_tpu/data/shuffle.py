"""Distributed two-phase shuffle over object-store blocks.

Role-equivalent of the reference's push-based shuffle
(``python/ray/data/_internal/push_based_shuffle.py``): a map phase
partitions every block into N sub-blocks (one multi-return remote task
per block — sub-blocks flow through the object store, never the
driver), and a reduce phase builds each output block from its N_map
parts.  The driver only routes ObjectRefs.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_util


def _partition_random(table, n_out: int, seed: int):
    rng = np.random.RandomState(seed)
    assign = rng.randint(0, n_out, size=table.num_rows)
    return [table.take(np.nonzero(assign == p)[0]) for p in range(n_out)]


def _partition_range(table, key: str, cuts, descending: bool):
    col = table.column(key).to_numpy(zero_copy_only=False)
    idx = np.searchsorted(cuts, col, side="right")
    if descending:
        idx = len(cuts) - idx
        idx = np.clip(idx, 0, len(cuts))
    return [table.take(np.nonzero(idx == p)[0])
            for p in range(len(cuts) + 1)]


def _stable_hash(x) -> int:
    """Deterministic across processes — Python's hash() is salted per
    process (PYTHONHASHSEED), which would scatter equal string keys into
    different partitions on different workers."""
    import zlib

    return zlib.crc32(repr(x).encode())


def _partition_hash(table, key: str, n_out: int):
    col = table.column(key).to_numpy(zero_copy_only=False)
    hashes = np.array([_stable_hash(x) % n_out for x in col.tolist()])
    return [table.take(np.nonzero(hashes == p)[0]) for p in range(n_out)]


@ray_tpu.remote
def _reduce_concat(*parts):
    live = [p for p in parts if p.num_rows]
    if not live:
        return parts[0]
    return block_util.concat_tables(live)


@ray_tpu.remote
def _reduce_sorted(key, descending, *parts):
    live = [p for p in parts if p.num_rows] or [parts[0]]
    big = block_util.concat_tables(live)
    order = "descending" if descending else "ascending"
    return big.sort_by([(key, order)])


def _two_phase(block_refs: List, n_out: int, map_remote,
               reduce_remote, reduce_args=()) -> List:
    """map: block -> n_out parts (multi-return); reduce: column of parts
    -> one output block."""
    maps = [map_remote.options(num_returns=n_out).remote(b)
            for b in block_refs]
    if n_out == 1:
        maps = [[m] for m in maps]
    return [reduce_remote.remote(*reduce_args,
                                 *[maps[m][p] for m in range(len(maps))])
            for p in range(n_out)]


@ray_tpu.remote
def _shuffle_map(table, seed: int, n_out: int):
    return tuple(_partition_random(table, n_out, seed)) \
        if n_out > 1 else table


def shuffle_blocks(block_refs: List, n_out: int,
                   seed: Optional[int] = None) -> List:
    """Random shuffle: every output block gets rows from every input."""
    base = np.random.RandomState(seed).randint(0, 2**31) \
        if seed is not None else np.random.randint(0, 2**31)
    maps = [_shuffle_map.options(num_returns=n_out).remote(
        b, base + i, n_out) for i, b in enumerate(block_refs)]
    if n_out == 1:
        maps = [[m] for m in maps]
    return [_reduce_concat.remote(*[maps[m][p]
                                    for m in range(len(maps))])
            for p in range(n_out)]


def sort_blocks(block_refs: List, key: str, descending: bool,
                n_out: int) -> List:
    """Sample-based range-partitioned distributed sort (reference:
    sort_impl's boundary sampling)."""
    @ray_tpu.remote
    def _sample(table):
        col = table.column(key).to_numpy(zero_copy_only=False)
        if len(col) == 0:
            return col
        k = min(64, len(col))
        idx = np.random.RandomState(0).choice(len(col), size=k,
                                              replace=False)
        return col[idx]

    samples = np.concatenate(
        [s for s in ray_tpu.get([_sample.remote(b) for b in block_refs],
                                timeout=300) if len(s)] or
        [np.array([0.0])])
    samples = np.sort(samples)
    cuts = [samples[int(len(samples) * (i + 1) / n_out)]
            for i in range(n_out - 1)] if n_out > 1 else []
    cuts_arr = np.asarray(sorted(set(cuts))) if cuts else np.asarray([])
    n_parts = len(cuts_arr) + 1

    @ray_tpu.remote
    def _map(table):
        parts = _partition_range(table, key, cuts_arr, descending)
        return tuple(parts) if n_parts > 1 else parts[0]

    maps = [_map.options(num_returns=n_parts).remote(b)
            for b in block_refs]
    if n_parts == 1:
        maps = [[m] for m in maps]
    # descending partitions are already emitted highest-first by
    # _partition_range's index flip
    return [_reduce_sorted.remote(key, descending,
                                  *[maps[m][p] for m in range(len(maps))])
            for p in range(n_parts)]


def hash_partition_blocks(block_refs: List, key: str, n_out: int) -> List:
    """Co-locate equal keys in the same output block (groupby basis)."""
    @ray_tpu.remote
    def _map(table):
        parts = _partition_hash(table, key, n_out)
        return tuple(parts) if n_out > 1 else parts[0]

    return _two_phase(block_refs, n_out, _map, _reduce_concat)
