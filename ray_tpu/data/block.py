"""Block utilities.  A block is a pyarrow.Table; BlockAccessor converts
between the user-facing batch formats (reference analog: data/block.py
BlockAccessor — numpy/pandas/arrow interconversion, fresh impl)."""

from __future__ import annotations

from typing import Any, Dict, List, Union

import numpy as np


def to_table(data) -> "pyarrow.Table":
    import pandas as pd
    import pyarrow as pa

    if isinstance(data, pa.Table):
        return data
    if isinstance(data, pd.DataFrame):
        return pa.Table.from_pandas(data, preserve_index=False)
    if isinstance(data, dict):
        return pa.table({k: np.asarray(v) for k, v in data.items()})
    if isinstance(data, np.ndarray):
        return pa.table({"value": data} if data.ndim == 1 else
                        {"value": list(data)})
    if isinstance(data, list):
        if data and isinstance(data[0], dict):
            cols: Dict[str, List[Any]] = {}
            for row in data:
                for k, v in row.items():
                    cols.setdefault(k, []).append(v)
            return pa.table(cols)
        return pa.table({"value": data})
    raise TypeError(f"cannot make a block from {type(data)}")


def format_batch(table, batch_format: str):
    if batch_format in ("pyarrow", "arrow"):
        return table
    if batch_format == "pandas":
        return table.to_pandas()
    if batch_format in ("numpy", "dict", "default"):
        return {name: col.to_numpy(zero_copy_only=False)
                for name, col in zip(table.column_names, table.columns)}
    raise ValueError(f"unknown batch_format {batch_format!r}")


def num_rows(table) -> int:
    return table.num_rows


def concat_tables(tables):
    import pyarrow as pa

    tables = [t for t in tables if t.num_rows]
    if not tables:
        import pyarrow as pa

        return pa.table({})
    return pa.concat_tables(tables)
