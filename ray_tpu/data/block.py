"""Block utilities.  A block is a pyarrow.Table; BlockAccessor converts
between the user-facing batch formats (reference analog: data/block.py
BlockAccessor — numpy/pandas/arrow interconversion, fresh impl)."""

from __future__ import annotations

from typing import Any, Dict, List, Union

import numpy as np


def _tensor_array(arr: np.ndarray):
    """N-D numpy column -> nested FixedSizeList arrow array (the role of
    the reference's ArrowTensorArray extension, data/extensions/
    tensor_extension.py): rows keep their (possibly multi-dim) shape
    through the block format and reassemble to numpy in format_batch."""
    import pyarrow as pa

    flat = pa.array(arr.reshape(-1))
    for dim in reversed(arr.shape[1:]):
        flat = pa.FixedSizeListArray.from_arrays(flat, dim)
    return flat


def _column_array(v):
    import pyarrow as pa

    arr = np.asarray(v)
    if arr.ndim > 1:
        return _tensor_array(arr)
    return pa.array(arr)


def to_table(data) -> "pyarrow.Table":
    import pandas as pd
    import pyarrow as pa

    if isinstance(data, pa.Table):
        return data
    if isinstance(data, pd.DataFrame):
        return pa.Table.from_pandas(data, preserve_index=False)
    if isinstance(data, dict):
        return pa.table({k: _column_array(v) for k, v in data.items()})
    if isinstance(data, np.ndarray):
        return pa.table({"value": pa.array(data) if data.ndim == 1 else
                         _tensor_array(data)})
    if isinstance(data, list):
        if data and isinstance(data[0], dict):
            cols: Dict[str, List[Any]] = {}
            for row in data:
                for k, v in row.items():
                    cols.setdefault(k, []).append(v)
            return pa.table(cols)
        return pa.table({"value": data})
    raise TypeError(f"cannot make a block from {type(data)}")


def format_batch(table, batch_format: str):
    if batch_format in ("pyarrow", "arrow"):
        return table
    if batch_format == "pandas":
        return table.to_pandas()
    if batch_format in ("numpy", "dict", "default"):
        import pyarrow as pa

        out = {}
        for name, col in zip(table.column_names, table.columns):
            typ = col.type
            if pa.types.is_fixed_size_list(typ):
                # tensor column: unnest FixedSizeList levels back to the
                # original (rows, *dims) numpy shape
                dims = []
                inner = typ
                while pa.types.is_fixed_size_list(inner):
                    dims.append(inner.list_size)
                    inner = inner.value_type
                arr = col.combine_chunks()
                flat = arr
                while hasattr(flat, "flatten") and \
                        pa.types.is_fixed_size_list(flat.type):
                    flat = flat.flatten()
                out[name] = flat.to_numpy(zero_copy_only=False).reshape(
                    (len(col), *dims))
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out
    raise ValueError(f"unknown batch_format {batch_format!r}")


def num_rows(table) -> int:
    return table.num_rows


def concat_tables(tables):
    import pyarrow as pa

    tables = [t for t in tables if t.num_rows]
    if not tables:
        import pyarrow as pa

        return pa.table({})
    return pa.concat_tables(tables)
