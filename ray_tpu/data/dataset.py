"""Dataset: lazy, fused, block-parallel transforms over the object store.

Reference analogs: python/ray/data/dataset.py (:319 map_batches, :950
split, :2422 iter_batches), read_api.py:227, _internal/plan.py:70
ExecutionPlan with stage fusion (:59 fuse).  Design deltas, TPU-first:
blocks are Arrow tables in shared memory (zero-copy to workers on the
same node), a chain of map-style stages compiles to ONE remote task per
block, and iter_batches can emit jax-ready numpy dicts for
Train ingest (`get_dataset_shard`).
"""

from __future__ import annotations

import builtins
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Union)

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_util

_DEFAULT_BLOCK_ROWS = 8192


def _fused_apply(table, stages):
    for fn in stages:
        table = fn(table)
    return table


@ray_tpu.remote
def _run_stages(table, stages):
    return _fused_apply(table, stages)


class Dataset:
    """A list of block ObjectRefs + pending (unfused) stages."""

    def __init__(self, block_refs: List, stages: Optional[List] = None):
        self._block_refs = list(block_refs)
        self._stages: List[Callable] = list(stages or [])

    # -- plan -------------------------------------------------------------
    def _with_stage(self, fn: Callable) -> "Dataset":
        return Dataset(self._block_refs, self._stages + [fn])

    def materialize(self) -> "Dataset":
        """Execute pending stages: one fused task per block (the stage-
        fusion property: N stages do NOT mean N tasks per block).  The
        result is cached in place, so repeated consumption (count() then
        iter_batches(), ...) never re-runs the pipeline."""
        if not self._stages:
            return self
        refs = [_run_stages.remote(b, self._stages)
                for b in self._block_refs]
        self._block_refs = refs
        self._stages = []
        return self

    def _tables(self) -> List:
        ds = self.materialize()
        return ray_tpu.get(list(ds._block_refs), timeout=300)

    # -- transforms (lazy) ------------------------------------------------
    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    **_unused) -> "Dataset":
        def stage(table):
            batch = block_util.format_batch(table, batch_format)
            return block_util.to_table(fn(batch))

        return self._with_stage(stage)

    def map(self, fn: Callable) -> "Dataset":
        def stage(table):
            rows = table.to_pylist()
            return block_util.to_table([fn(r) for r in rows])

        return self._with_stage(stage)

    def filter(self, fn: Callable) -> "Dataset":
        def stage(table):
            rows = [r for r in table.to_pylist() if fn(r)]
            if not rows:
                return table.slice(0, 0)
            return block_util.to_table(rows)

        return self._with_stage(stage)

    def flat_map(self, fn: Callable) -> "Dataset":
        def stage(table):
            out = []
            for r in table.to_pylist():
                out.extend(fn(r))
            if not out:
                return table.slice(0, 0)
            return block_util.to_table(out)

        return self._with_stage(stage)

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def stage(table):
            batch = block_util.format_batch(table, "numpy")
            batch[name] = np.asarray(fn(batch))
            return block_util.to_table(batch)

        return self._with_stage(stage)

    # -- geometry ---------------------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        tables = self._tables()
        big = block_util.concat_tables(tables)
        n = big.num_rows
        sizes = [(n + i) // num_blocks
                 for i in builtins.range(num_blocks)]
        refs, start = [], 0
        for s in sizes:
            refs.append(ray_tpu.put(big.slice(start, s)))
            start += s
        return Dataset(refs)

    def split(self, n: int, *, equal: bool = True) -> List["Dataset"]:
        """Per-consumer shards (reference dataset.py:950; Train ingest
        path train/_internal/dataset_spec.py:66 get_dataset_shards)."""
        ds = self.materialize()
        if equal or len(ds._block_refs) % n:
            ds = ds.repartition(n)  # near-equal row counts per block
        per = len(ds._block_refs) // n
        return [Dataset(ds._block_refs[i * per:(i + 1) * per])
                for i in builtins.range(n)]

    def union(self, *others: "Dataset") -> "Dataset":
        ds = self.materialize()
        refs = list(ds._block_refs)
        for o in others:
            refs.extend(o.materialize()._block_refs)
        return Dataset(refs)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        tables = self._tables()
        big = block_util.concat_tables(tables)
        rng = np.random.RandomState(seed)
        perm = rng.permutation(big.num_rows)
        shuffled = big.take(perm)
        k = max(1, len(self._block_refs))
        out = Dataset([ray_tpu.put(shuffled)]).repartition(k)
        return out

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        tables = self._tables()
        big = block_util.concat_tables(tables)
        order = "descending" if descending else "ascending"
        big = big.sort_by([(key, order)])
        return Dataset([ray_tpu.put(big)]).repartition(
            max(1, len(self._block_refs)))

    # -- consumption ------------------------------------------------------
    def count(self) -> int:
        return sum(t.num_rows for t in self._tables())

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for t in self._tables():
            out.extend(t.to_pylist())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Dict[str, Any]]:
        return [r for t in self._tables() for r in t.to_pylist()]

    def schema(self):
        if not self._block_refs:
            return None
        if self._stages:  # run the fused pipeline on ONE block only
            ref = _run_stages.remote(self._block_refs[0], self._stages)
            return ray_tpu.get([ref], timeout=60)[0].schema
        return ray_tpu.get([self._block_refs[0]], timeout=60)[0].schema

    @property
    def num_blocks(self) -> int:
        return len(self._block_refs)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator:
        carry = None
        for t in self._tables():
            if carry is not None and carry.num_rows:
                t = block_util.concat_tables([carry, t])
            start = 0
            while t.num_rows - start >= batch_size:
                yield block_util.format_batch(
                    t.slice(start, batch_size), batch_format)
                start += batch_size
            carry = t.slice(start)
        if carry is not None and carry.num_rows and not drop_last:
            yield block_util.format_batch(carry, batch_format)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for t in self._tables():
            yield from t.to_pylist()

    def to_pandas(self):
        return block_util.concat_tables(self._tables()).to_pandas()

    def to_numpy_refs(self) -> List:
        ds = self.materialize()
        return list(ds._block_refs)

    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, t in enumerate(self._tables()):
            pq.write_table(t, os.path.join(path, f"part-{i:05d}.parquet"))

    def __repr__(self):
        return (f"Dataset(num_blocks={self.num_blocks}, "
                f"pending_stages={len(self._stages)})")


# -- creation APIs ---------------------------------------------------------

def _split_rows(n_rows: int, parallelism: int) -> List[builtins.range]:
    per = max(1, n_rows // max(1, parallelism))
    return [builtins.range(i, min(i + per, n_rows))
            for i in builtins.range(0, n_rows, per)]


def from_items(items: Sequence[Any], *, parallelism: int = 8) -> Dataset:
    refs = []
    for rng in _split_rows(len(items), parallelism):
        chunk = [items[i] for i in rng]
        refs.append(ray_tpu.put(block_util.to_table(chunk)))
    return Dataset(refs)


def range(n: int, *, parallelism: int = 8) -> Dataset:
    refs = [ray_tpu.put(block_util.to_table(
        {"id": np.arange(r.start, r.stop, dtype=np.int64)}))
        for r in _split_rows(n, parallelism)]
    return Dataset(refs)


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]], *,
               parallelism: int = 8) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"value": arrays}
    n = len(next(iter(arrays.values())))
    refs = [ray_tpu.put(block_util.to_table(
        {k: v[r.start:r.stop] for k, v in arrays.items()}))
        for r in _split_rows(n, parallelism)]
    return Dataset(refs)


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df, preserve_index=False)
    return from_arrow(table, parallelism=parallelism)


def from_arrow(table, *, parallelism: int = 8) -> Dataset:
    refs = [ray_tpu.put(table.slice(r.start, r.stop - r.start))
            for r in _split_rows(table.num_rows, parallelism)]
    return Dataset(refs)


def read_parquet(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os

    import pyarrow.parquet as pq

    files = sorted(glob.glob(os.path.join(path, "*.parquet"))) \
        if os.path.isdir(path) else [path]
    if not files:
        raise FileNotFoundError(f"no parquet files under {path}")
    refs = [ray_tpu.put(pq.read_table(f)) for f in files]
    return Dataset(refs)


def read_csv(path: str, *, parallelism: int = 8) -> Dataset:
    import glob
    import os

    from pyarrow import csv as pa_csv

    files = sorted(glob.glob(os.path.join(path, "*.csv"))) \
        if os.path.isdir(path) else [path]
    if not files:
        raise FileNotFoundError(f"no csv files under {path}")
    refs = [ray_tpu.put(pa_csv.read_csv(f)) for f in files]
    return Dataset(refs)
